"""Autoregressive generation engine: continuous batching on the decode
plane.

The forward batcher (``scheduler.ServingEngine``) amortizes ONE program
dispatch across requests; generation needs the same economics across
*tokens*.  A naive deployment re-runs the full forward for every
generated token (re-paying attention over the whole prefix — the
``serving.decode.reprefill`` bench baseline); this engine runs the
prompt ONCE (prefill, filling the KV cache) and then advances every
in-flight sequence one token per compiled decode step, admitting newly
prefilled sequences into the running batch between steps and retiring
finished ones (EOS / ``max_tokens``) — continuous batching, the regime
where decode throughput stops being per-request and becomes
per-step.

One engine thread owns the loop:

* **pump** — drain the submit queue into per-model FIFO waiting deques
  (blocking only when there is no admitted work at all);
* **admit** — take waiting requests (FIFO, never overtaking — pinned by
  the seeded-loadgen test), run one bucketed prefill batch
  (``serve_prefill`` phase), sample each sequence's first token, and
  copy its cache rows into free decode slots;
* **decode** — one compiled step per model with active slots
  (``serve_decode`` phase): the batch's next-token vector goes in, the
  donated KV cache is updated in place, and — in the default
  ``MXNET_SERVE_SAMPLE=graph`` mode — sampling (greedy, or seeded
  temperature/top-k per request) runs INSIDE the program over per-slot
  PRNG key state that rides as another donated argument, so the only
  per-step host transfer is the ``(slots,)`` token vector.
  ``MXNET_SERVE_SAMPLE=host`` is the escape hatch: the logits-out
  decode program plus the SAME jitted sampler on the host-fetched
  ``(slots, vocab)`` matrix — byte-identical token streams, one big
  fetch per step (``stats()["decode_fetch_elems"]`` counts the
  difference; the profiler's ``serve_sample`` phase brackets it);
* **retire** — a sequence hitting its ``eos_id`` or ``max_tokens``
  resolves its Future with a :class:`GenerationResult` (and closes its
  :class:`TokenStream`, if streaming); its slot frees for the next
  admission.

The KV cache is registry-owned serving state: it lives beside the
params on the model's :class:`~.program_store.GenerativeProgramStore`
(one device-resident copy in the store's ``kv_dtype`` —
``MXNET_SERVE_KV_DTYPE=bfloat16`` halves the bytes per slot;
``stats()`` describes it) and is threaded through the pure decode
programs cache-in/cache-out with donation, so the per-step write is an
in-place ``dynamic_update_slice`` on the resident buffers (donation is
skipped on the CPU backend, matching the training planes' donation
guards).

On the default PAGED plane (``MXNET_SERVE_PAGED=1``) the cache is a
single global pool of ``MXNET_SERVE_KV_BLOCK``-token blocks addressed
through per-slot block tables (:class:`_PagedModelState`): admission
reserves each request's worst-case block need up front (throttling
FIFO when the pool runs short — the pool can never exhaust
mid-flight), completed prefills register their blocks in a
copy-on-write prefix cache (:class:`_PrefixStore` — an identical
prompt prefix adopts the shared blocks instead of re-prefilling;
writes into shared blocks fork first), and prompts prefill in
``MXNET_SERVE_PREFILL_CHUNK``-token chunks AFTER each tick's decode
step so long prompts stop spiking co-running streams' inter-token
latency.  ``paged=False`` (or ``MXNET_SERVE_PAGED=0``) keeps the
contiguous per-slot plane above, bit-identical streams
(docs/architecture/decode_engine.md).

``close(drain=True)`` finishes every admitted AND queued generation
before the thread exits; ``close(drain=False)`` fails everything fast
with :class:`~.scheduler.ServeClosed`.
"""
from __future__ import annotations

import collections
import queue
import sys
import threading
import time
from concurrent.futures import Future

import jax
import jax.numpy as jnp
import numpy as np

from .. import metrics as _metrics
from .. import profiler as _profiler
from .. import tracing as _tracing
from ..analysis import racecheck
from ..analysis.lockcheck import make_lock
from ..base import MXNetError, _uid, get_env, hot_path
from .scheduler import (FutureCompleter, ServeClosed, ServeOverloaded,
                        ServeTimeout, TIERS)

# Aggregate generation histograms (process-wide; gated on
# MXNET_METRICS like every ambient observation seam).  TTFT and ITL
# are THE generation service metrics — the /metrics scrape carries
# their p50/p95/p99 without storing a sample per token.
_H_TTFT = _metrics.histogram(
    "serve_ttft_seconds",
    help="generation time-to-first-token, submit to first sample")
_H_ITL = _metrics.histogram(
    "serve_itl_seconds",
    help="generation inter-token latency, gap between samples")
_H_CHUNKS = _metrics.histogram(
    "serve_prefill_chunks_per_request",
    help="chunked-prefill dispatches one admitted request's prompt "
         "took on the paged decode plane", lo=1, hi=1e4)
_H_SPEC = _metrics.histogram(
    "serve_spec_emitted_per_step",
    help="tokens emitted per speculative verify step (1..K+1: "
         "accepted draft tokens + the bonus/corrected token; "
         "acceptance rate is (emitted-1) over the proposal window)",
    lo=1, hi=64)

# MXNET_SERVE_SPEC=auto's graceful-degradation policy: when the rolling
# acceptance EMA falls below the floor (the draft is fighting the
# target — adversarial prompts, mismatched domains), the engine stops
# paying for drafts and serves plain decode steps, PROBING one
# speculative tick every _SPEC_PROBE_EVERY ticks so a recovered draft
# re-engages.  Probes catch the draft's KV frontier up in
# prefill_chunk-sized teacher-forced dispatches, so a probe costs a few
# draft calls, not one per skipped token — and FAILED probes back off
# exponentially (doubling the cadence up to _SPEC_PROBE_MAX; recovery
# resets it), so a persistently hostile workload converges to
# near-zero speculation overhead instead of paying a fixed probe tax.
_SPEC_EMA_DECAY = 0.75
_SPEC_EMA_FLOOR = 0.125
_SPEC_PROBE_EVERY = 128
_SPEC_PROBE_MAX = 2048

__all__ = ["GenerationEngine", "GenerationResult", "TokenStream"]

_STOP = object()


class GenerationResult:
    """One finished generation (what the request's Future resolves to).

    ``tokens`` — the generated ids (prompt excluded); ``finish_reason``
    — ``'eos'`` or ``'length'``; ``token_times`` — host
    ``perf_counter()`` stamps taken as each token was sampled, so
    clients (and the loadgen) derive TTFT (``token_times[0] -
    t_submit``) and inter-token latency without streaming machinery."""

    __slots__ = ("model", "prompt_len", "tokens", "finish_reason",
                 "t_submit", "token_times")

    def __init__(self, model, prompt_len, tokens, finish_reason,
                 t_submit, token_times):
        self.model = model
        self.prompt_len = prompt_len
        self.tokens = tokens
        self.finish_reason = finish_reason
        self.t_submit = t_submit
        self.token_times = token_times

    @property
    def ttft_s(self):
        """Submit -> first generated token (seconds)."""
        return self.token_times[0] - self.t_submit

    def itl_s(self):
        """Inter-token gaps (seconds), one per token after the first."""
        return [b - a for a, b in zip(self.token_times,
                                      self.token_times[1:])]

    def __repr__(self):
        return ("GenerationResult(model=%r, %d tokens, %s)"
                % (self.model, len(self.tokens), self.finish_reason))


class TokenStream:
    """Blocking per-sequence token iterator.

    Construct one and pass it to :meth:`GenerationEngine.submit`
    (``stream=``): the engine pushes each sampled token id as it is
    generated and closes the stream when the sequence retires, so
    ``for tok in stream: ...`` sees tokens at inter-token latency
    instead of waiting for the Future."""

    _CLOSE = object()

    def __init__(self):
        self._q = queue.Queue()

    def push(self, token):
        self._q.put(int(token))

    def close(self):
        self._q.put(self._CLOSE)

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._CLOSE:
            raise StopIteration
        return item


class _GenRequest:
    __slots__ = ("model", "prompt", "max_tokens", "temperature", "top_k",
                 "seed", "eos_id", "stream", "future", "deadline",
                 "t_submit", "tokens", "token_times", "seq", "priority",
                 "tenant", "trace", "trace_parent")

    def __init__(self, model, prompt, max_tokens, temperature, top_k,
                 seed, eos_id, stream, future, deadline, t_submit, seq,
                 priority="batch", tenant=None):
        self.model = model
        self.prompt = prompt
        self.max_tokens = max_tokens
        self.temperature = temperature
        self.top_k = top_k
        self.seed = int(seed)
        self.eos_id = eos_id
        self.stream = stream
        self.future = future
        self.deadline = deadline
        self.t_submit = t_submit
        self.tokens = []
        self.token_times = []
        self.seq = seq
        self.priority = priority  # admission tier (scheduler.TIERS)
        self.tenant = tenant      # quota/metrics key, or None
        # trace context captured on the submitting thread and
        # re-activated around this request's prefill/decode dispatches
        self.trace = None
        self.trace_parent = None


class _ModelState:
    """Live decode batch of one model: slot table + the KV cache +
    per-slot sampling state (PRNG key chain, temperature, top-k)."""

    def __init__(self, store):
        self.store = store
        self.slots = []                      # _GenRequest or None
        self.lengths = np.zeros(0, np.int32)   # cache frontier per slot
        self.next_tok = np.zeros(0, np.int32)  # next token to consume
        self.temps = np.zeros(0, np.float32)   # <= 0 means greedy
        self.top_ks = np.zeros(0, np.int32)
        self.keys = jnp.zeros((0, 2), jnp.uint32)  # threefry key data
        self.cache_k = None
        self.cache_v = None
        self.C = 0                           # current cache bucket

    def active(self):
        return [i for i, r in enumerate(self.slots) if r is not None]

    def free_slot(self):
        for i, r in enumerate(self.slots):
            if r is None:
                return i
        return None

    def describe(self):
        act = self.active()
        d = {"slots": len(self.slots), "active": len(act),
             "cache_len": self.C,
             "sample_mode": self.store.sample_mode}
        if self.cache_k is not None:
            total = 2 * self.cache_k.size * self.cache_k.dtype.itemsize
            d["cache_mb"] = round(total / 2**20, 3)
            d["cache_dtype"] = str(self.cache_k.dtype)
            # the bf16 claim's measurement: bytes one slot's cache rows
            # occupy at the current bucket depth (halved vs fp32)
            if self.slots:
                d["cache_bytes_per_slot"] = total // len(self.slots)
        return d


class _BlockPool:
    """Host-side allocator over the paged KV pool's physical blocks.

    Block 0 is the reserved trash block (zero table entries point at
    it; non-participating dispatch rows scribble there) and is never
    allocated.  Every allocated block carries a refcount: a sequence
    holding it in its table counts one, each prefix-cache pin counts
    one — a block frees when the last reference drops."""

    def __init__(self, num_blocks):
        self.num_blocks = int(num_blocks)
        self._free = list(range(self.num_blocks - 1, 0, -1))
        self._ref = {}
        self._hwm = 0
        # the engine thread mutates the allocator (admission /
        # retirement); stats() -> describe() reads it from client
        # threads.  The lock makes those reads coherent; the coarse
        # shared_state revision marker lets MXNET_RACE_CHECK=1 catch
        # any future unlocked path through the pool
        self._lock = make_lock("serving.gen.block_pool")
        self._rc = racecheck.shared_state("serving.gen.block_pool",
                                          rev=0)

    def capacity(self):
        return self.num_blocks - 1

    @property
    def hwm(self):
        with self._lock:
            _ = self._rc.rev
            return self._hwm

    def used(self):
        with self._lock:
            _ = self._rc.rev
            return self.capacity() - len(self._free)

    def free_count(self):
        with self._lock:
            _ = self._rc.rev
            return len(self._free)

    def refcount(self, b):
        with self._lock:
            _ = self._rc.rev
            return self._ref.get(b, 0)

    def alloc(self):
        """One fresh block at refcount 1, or None when exhausted."""
        with self._lock:
            self._rc.rev += 1
            if not self._free:
                return None
            b = self._free.pop()
            self._ref[b] = 1
            used = self.capacity() - len(self._free)
            if used > self._hwm:
                self._hwm = used
            return b

    def ref(self, b):
        with self._lock:
            self._rc.rev += 1
            self._ref[b] += 1

    def deref(self, b):
        with self._lock:
            self._rc.rev += 1
            r = self._ref[b] - 1
            if r <= 0:
                del self._ref[b]
                self._free.append(b)
            else:
                self._ref[b] = r
            return r

    def shared(self):
        """Blocks currently referenced more than once."""
        with self._lock:
            _ = self._rc.rev
            return sum(1 for r in self._ref.values() if r > 1)


class _PrefixStore:
    """Copy-on-write prefix cache: exact prompt prefixes -> pinned
    pool blocks.

    Keys are the token tuples themselves (no hash collisions): a full
    block j of a completed prefill registers under
    ``tuple(prompt[:(j+1)*bs])``; a partial tail block under the WHOLE
    prompt tuple.  Each entry pins one refcount on its block, so
    shared prefixes survive their registering sequence's retirement.
    Matching walks full blocks longest-prefix-first and takes the
    tail only on an exact whole-prompt match — N requests with the
    same system prompt pay its prefill once.  Entries whose pin is
    the LAST reference are evictable (LRU) when the pool runs dry."""

    def __init__(self, pool, block_size):
        self._pool = pool
        self._bs = int(block_size)
        self._entries = collections.OrderedDict()  # tokens -> (blk, n)

    def __len__(self):
        return len(self._entries)

    def match(self, prompt):
        """Longest shared prefix of ``prompt``: ``(full_blocks, tail)``
        — physical block ids for whole shared blocks, plus the tail
        block on an exact whole-prompt match (else None).  Touches the
        matched entries' LRU position; refcounts are NOT taken (the
        caller refs what it actually adopts)."""
        bs = self._bs
        blocks = []
        j = 0
        while (j + 1) * bs <= len(prompt):
            key = tuple(prompt[:(j + 1) * bs])
            e = self._entries.get(key)
            if e is None or e[1] != bs:
                break
            self._entries.move_to_end(key)
            blocks.append(e[0])
            j += 1
        tail = None
        nt = len(prompt) % bs
        if nt and j == len(prompt) // bs:
            e = self._entries.get(tuple(prompt))
            if e is not None and e[1] == nt:
                self._entries.move_to_end(tuple(prompt))
                tail = e[0]
        return blocks, tail

    def register(self, prompt, table_row):
        """Pin a completed prefill's blocks for future sharing (+1
        refcount per NEW entry; prefixes already registered — possibly
        against different physical blocks — are left alone)."""
        bs = self._bs
        for j in range(len(prompt) // bs):
            key = tuple(prompt[:(j + 1) * bs])
            if key in self._entries:
                continue
            b = int(table_row[j])
            self._pool.ref(b)
            self._entries[key] = (b, bs)
        nt = len(prompt) % bs
        if nt:
            key = tuple(prompt)
            if key not in self._entries:
                b = int(table_row[len(prompt) // bs])
                self._pool.ref(b)
                self._entries[key] = (b, nt)

    def evictable(self):
        """Pins whose block would FREE on eviction (refcount 1)."""
        return sum(1 for b, _n in self._entries.values()
                   if self._pool.refcount(b) == 1)

    def evict_one(self):
        """Drop the least-recently-used pin whose block frees (blocks
        still held by live sequences stay).  True when a block was
        reclaimed."""
        for key, (b, _n) in self._entries.items():
            if self._pool.refcount(b) == 1:
                del self._entries[key]
                self._pool.deref(b)
                return True
        return False


class _PagedModelState:
    """Live paged decode batch of one model: slot table + per-slot
    block tables over the global KV pool + the prefix cache.

    Unlike the contiguous :class:`_ModelState`, this PERSISTS across
    batch drains — the prefix cache's pinned blocks are the point of
    keeping it — so ``store.cache_state`` stays attached until the
    engine closes."""

    paged = True

    def __init__(self, store, draft=None, spec_k=0):
        self.store = store
        self.pool = _BlockPool(store.pool_blocks)
        self.prefix = _PrefixStore(self.pool, store.kv_block)
        self.pool_k, self.pool_v = store.new_pool()
        # int8 plane: the per-(layer, head, block) fp32 absmax scale
        # pools ride beside the code pools through every dispatch
        self.scales = (store.new_scale_pool() if store.kv_int8
                       else None)
        self.tb = store.table_width()
        self.slots = []                        # _GenRequest or None
        self.tables = np.zeros((0, self.tb), np.int32)
        self.lengths = np.zeros(0, np.int32)   # KV frontier per slot
        self.prog = np.zeros(0, np.int32)      # prompt tokens consumed
        self.decoding = np.zeros(0, bool)      # prompt done, generating
        self.chunks_done = np.zeros(0, np.int32)
        self.next_tok = np.zeros(0, np.int32)
        self.temps = np.zeros(0, np.float32)
        self.top_ks = np.zeros(0, np.int32)
        self.resv = np.zeros(0, np.int32)      # reserved-unallocated
        self.keys = jnp.zeros((0, 2), jnp.uint32)
        self.g_used = None                     # pool gauges (engine)
        self.g_hwm = None
        self.g_bytes = None
        # speculative decoding: the draft model's OWN pool arrays ride
        # the target's block tables (one allocator, two KV planes) —
        # dlen is the draft's per-slot KV frontier, dkeys its
        # independent per-slot PRNG chains
        self.draft = draft
        self.spec_k = int(spec_k)
        if draft is not None:
            self.dpool_k, self.dpool_v = draft.new_pool()
            self.dscales = (draft.new_scale_pool() if draft.kv_int8
                            else None)
            self.dlen = np.zeros(0, np.int32)
            # host-resident between spec ticks: admission writes
            # single rows, and only a spec tick's sampler needs the
            # device copy (it converts back when it finishes)
            self.dkeys = np.zeros((0, 2), np.uint32)
            # auto-mode degradation state: rolling acceptance EMA +
            # the probe countdown while speculating is suspended
            self.spec_ema = 1.0
            self.spec_probe = _SPEC_PROBE_EVERY
            self.spec_probe_every = _SPEC_PROBE_EVERY
            self.spec_forced = False

    def spec_mirror(self):
        """Whether prefill chunks mirror into the draft KV plane:
        always while speculating, skipped while the auto-mode fallback
        has speculation suspended (probe catch-up rebuilds the draft
        KV from the prompt when needed)."""
        return self.spec_forced or self.spec_ema >= _SPEC_EMA_FLOOR

    def active(self):
        return [i for i, r in enumerate(self.slots) if r is not None]

    def free_slot(self):
        for i, r in enumerate(self.slots):
            if r is None:
                return i
        return None

    def reserved_total(self):
        return int(self.resv.sum())

    def describe(self):
        act = self.active()
        # dtype-aware pool bytes: int8 code pools carry their fp32
        # scale pools — a block is only decodable as codes+scale, so
        # the memory claim counts both (the PR-12 weight_bytes
        # discipline applied to the KV plane)
        pool_bytes = 2 * self.pool_k.size * self.pool_k.dtype.itemsize
        if self.scales is not None:
            pool_bytes += 2 * self.scales[0].size * \
                self.scales[0].dtype.itemsize
        per_block = pool_bytes // self.store.pool_blocks
        d = {"slots": len(self.slots), "active": len(act),
             "paged": True,
             "sample_mode": self.store.sample_mode,
             "block_size": self.store.kv_block,
             "prefill_chunk": self.store.prefill_chunk,
             "pool_blocks": self.pool.capacity(),
             "pool_blocks_used": self.pool.used(),
             "pool_blocks_hwm": self.pool.hwm,
             "pool_blocks_shared": self.pool.shared(),
             "pool_blocks_reserved": self.reserved_total(),
             "prefix_entries": len(self.prefix),
             "cache_mb": round(pool_bytes / 2**20, 3),
             "pool_bytes": pool_bytes,
             "pool_bytes_used": self.pool.used() * per_block,
             "pool_bytes_per_token":
                 per_block / self.store.kv_block,
             "block_bytes": per_block,
             "cache_dtype": str(self.pool_k.dtype)}
        if self.draft is not None:
            dbytes = 2 * self.dpool_k.size * self.dpool_k.dtype.itemsize
            if self.dscales is not None:
                dbytes += 2 * self.dscales[0].size * \
                    self.dscales[0].dtype.itemsize
            d["spec_k"] = self.spec_k
            d["draft_pool_bytes"] = dbytes
            d["spec_acceptance_ema"] = round(float(self.spec_ema), 4)
        if act:
            # the paged memory claim's measurement: pool bytes
            # actually BACKING the live sequences, per sequence —
            # shared prefix blocks are paid once, so prefix-heavy
            # schedules drive this far under the contiguous plane's
            # cache_bytes_per_slot
            d["cache_bytes_per_active_seq"] = \
                (self.pool.used() * per_block) // len(act)
        return d


class GenerationEngine:
    """Continuous-batching autoregressive generation over a
    :class:`~.registry.ModelRegistry`'s generative models.

    ``submit(model, tokens, ...)`` returns a
    ``concurrent.futures.Future`` resolving to a
    :class:`GenerationResult`.  One engine serves every generative
    model in the registry; prefill batches and decode steps never mix
    models.
    """

    def __init__(self, registry, max_active=None, max_inflight=None,
                 owner_index=None, tenant_quotas=None):
        self._registry = registry
        self._max_active = (int(max_active) if max_active is not None
                            else None)
        if max_inflight is None:
            max_inflight = int(get_env("MXNET_SERVE_MAX_INFLIGHT"))
        self._max_inflight = max(0, int(max_inflight))  # 0 = unbounded
        self._inflight = 0
        # owning replica index (None = bare engine): every ServeClosed
        # minted here carries it — see scheduler.ServeClosed
        self._owner_index = owner_index
        # per-tenant admission quotas: tenant id -> max inflight TOKENS
        # (prompt + max_tokens over the tenant's unresolved requests)
        self._tenant_quotas = dict(tenant_quotas or {})
        # tenant ledger + lifecycle flags live in racecheck containers
        # (plain dict / SimpleNamespace with the detector off): under
        # MXNET_RACE_CHECK=1 any access that skipped the _submit_lock
        # edge raises DataRaceError instead of silently going stale
        self._tenant_tokens = racecheck.shared_map(
            "serving.gen.tenant_tokens")
        self._queue = queue.Queue()
        self._waiting = {}     # model -> deque[_GenRequest]
        self._states = {}      # model -> _ModelState
        self._life = racecheck.shared_state(
            "serving.gen.lifecycle", closed=False, drain_on_stop=True)
        self._seq = 0
        self._submit_lock = make_lock("serving.gen_submit")
        self._stats_lock = make_lock("serving.gen_stats")
        # counters live in the process metrics registry (one labeled
        # series per engine); stats() reads THROUGH them —
        # decode_fetch_elems counts host elements fetched from
        # decode-step outputs (tokens in graph-sampling mode, logits in
        # host mode): per decode_step it is the per-step fetch
        # footprint the in-graph sampler shrinks from (slots, vocab)
        # to (slots,) — pinned by tests
        self._mlabels = {"engine": "gen%d" % _uid()}
        self._stats = _metrics.CounterDict(
            "serve_gen_",
            ("requests", "prefills", "prefill_seqs", "decode_steps",
             "generated_tokens", "finished", "timeouts", "cancelled",
             "errors", "shed", "cache_grows", "slot_grows",
             "decode_fetch_elems",
             # paged-plane counters (zero on contiguous engines):
             # prefix_hits counts admissions that reused shared
             # blocks, *_blocks/_tokens their sizes; cow_forks the
             # copy-on-write block duplications; prefill_chunks the
             # chunk dispatches; shed_pool the requests too large for
             # the pool
             "prefix_hits", "prefix_hit_blocks", "prefix_hit_tokens",
             "cow_forks", "prefill_chunks", "shed_pool",
             # speculative decoding (zero without a draft attached):
             # spec_steps counts verify dispatches (each is ONE target
             # step emitting 1..K+1 tokens), spec_proposed/spec_
             # accepted the draft tokens offered/accepted, spec_draft_
             # steps the draft micro-dispatches (catch-up + proposal)
             "spec_steps", "spec_proposed", "spec_accepted",
             "spec_draft_steps", "spec_fallback_steps"),
            labels=self._mlabels, help="generation engine counter")
        self._g_inflight = _metrics.gauge(
            "serve_gen_inflight", labels=self._mlabels,
            help="accepted-but-unresolved generation requests")
        self._max_active_seen = 0   # high-water mark (stats)
        # high-water cache geometry per model (survives the cache being
        # dropped when a batch drains — the bf16 bytes-per-slot bench
        # evidence reads this instead of racing a live batch)
        self._cache_hwm = {}
        # test seam: (model, seq) admission order; bounded so a
        # long-lived serving process never accumulates it
        self._admit_log = collections.deque(maxlen=4096)
        self._admit_fns = {}   # (prefill shape, cache shape) -> jitted
        self._completer = FutureCompleter("mxt-gen-done")
        self._thread = threading.Thread(target=self._serve_loop,
                                        name="mxt-gen", daemon=True)
        self._thread.start()

    def _closed_exc(self, msg):
        return ServeClosed(msg, replica_index=self._owner_index)

    # lifecycle flags route through the shared_state container so the
    # race detector sees every access; call sites keep the field names
    @property
    def _closed(self):
        return self._life.closed

    @_closed.setter
    def _closed(self, v):
        self._life.closed = v

    @property
    def _drain_on_stop(self):
        return self._life.drain_on_stop

    @_drain_on_stop.setter
    def _drain_on_stop(self, v):
        self._life.drain_on_stop = v

    # -- client side ---------------------------------------------------
    def submit(self, model, tokens, max_tokens=16, temperature=0.0,
               top_k=0, seed=0, eos_id=None, stream=None, timeout=None,
               priority=None, tenant=None):
        """Enqueue one generation request; returns its Future.

        ``tokens`` — prompt token ids (non-empty); ``max_tokens`` —
        generation cap (>= 1; the prompt+generation total must fit
        ``MXNET_SERVE_KV_MAX``); ``temperature <= 0`` is greedy,
        otherwise seeded temperature sampling over the ``top_k``
        highest logits (``top_k=0`` = full vocab) — the token stream is
        a pure function of ``seed`` (a per-request threefry key chain,
        split once per token), identical under in-graph AND host
        sampling and invariant to batch composition; ``eos_id`` stops
        early; ``stream`` — an optional :class:`TokenStream` receiving
        tokens as they are sampled; ``timeout`` (seconds) bounds
        time-to-admission.

        ``priority`` ("latency"/"batch", default "batch") orders the
        waiting deque: latency requests admit before batch requests of
        the same model.  ``tenant`` keys the per-tenant TOKEN quota
        (constructor ``tenant_quotas``: prompt+max_tokens over the
        tenant's unresolved requests) — a tenant over budget is shed
        alone with :class:`ServeOverloaded`."""
        with self._submit_lock:
            # early gate (under the lock that orders it against
            # close()): every post-close submit raises ServeClosed,
            # never a validation error about its payload
            if self._closed:
                raise self._closed_exc("generation engine is closed")
        priority = "batch" if priority is None else str(priority)
        if priority not in TIERS:
            raise MXNetError("unknown priority tier %r (want one of %s)"
                             % (priority, "/".join(TIERS)))
        tenant = None if tenant is None else str(tenant)
        store = self._registry.gen_store(model)
        # coerce EVERY request field up front, mapping coercion errors
        # to MXNetError (the front door's 400 class — a malformed body
        # is a client error, not a 500) and, crucially, BEFORE the
        # admission bookkeeping: a ValueError after the inflight
        # increment would leak the budget slot forever (no future ever
        # carries the decrement)
        try:
            prompt = [int(t) for t in tokens]
            max_tokens = int(max_tokens)
            temperature = float(temperature)
            top_k = int(top_k)
            seed = int(seed)
            eos_id = None if eos_id is None else int(eos_id)
            timeout = None if timeout is None else float(timeout)
        except (TypeError, ValueError) as e:
            raise MXNetError("invalid generation parameter: %s" % e)
        if not prompt:
            raise MXNetError("empty prompt")
        vocab = store.spec["vocab_size"]
        if min(prompt) < 0 or max(prompt) >= vocab:
            raise MXNetError("prompt token out of range [0, %d)" % vocab)
        if max_tokens < 1:
            raise MXNetError("max_tokens must be >= 1")
        store.validate_request(len(prompt), max_tokens)
        fut = Future()
        now = time.monotonic()
        # trace context: an ingress trace active on this thread (HTTP
        # handler, replica-set placement) rides the request; a bare
        # in-process submit mints its own
        ctx = _tracing.current_context()
        owned = None
        if ctx is None:
            owned = _tracing.start_trace("serve.generate", model=model)
            ctx = (owned, owned.root_id)
        cost = len(prompt) + max_tokens   # the tenant-quota unit
        try:
            with self._submit_lock:
                if self._closed:
                    raise self._closed_exc("generation engine is closed")
                if self._max_inflight \
                        and self._inflight >= self._max_inflight:
                    self._stats.inc("shed")
                    raise ServeOverloaded(
                        "generation engine is at its inflight budget "
                        "(%d); request shed — back off and retry"
                        % self._max_inflight)
                quota = self._tenant_quotas.get(tenant) \
                    if tenant is not None else None
                if quota is not None and \
                        self._tenant_tokens.get(tenant, 0) + cost > quota:
                    # only the noisy tenant sheds; other tenants'
                    # admission is untouched
                    self._stats.inc("shed")
                    _metrics.cached_counter(
                        "serve_tenant_shed_total",
                        labels={"tenant": tenant},
                        help="requests shed by per-tenant quota").inc()
                    raise ServeOverloaded(
                        "tenant %r is over its inflight token quota "
                        "(%d); request shed — back off and retry"
                        % (tenant, quota))
                self._inflight += 1
                if tenant is not None:
                    self._tenant_tokens[tenant] = \
                        self._tenant_tokens.get(tenant, 0) + cost
                self._g_inflight.set(self._inflight)
                req = _GenRequest(
                    model, prompt, max_tokens, temperature,
                    top_k, seed, eos_id, stream, fut,
                    now + timeout if timeout is not None else None,
                    time.perf_counter(), self._seq,
                    priority=priority, tenant=tenant)
                req.trace, req.trace_parent = ctx
                self._seq += 1
                self._queue.put(req)
        except (ServeClosed, ServeOverloaded) as e:
            # export the self-minted trace with the shed/closed status
            # (outside the lock) instead of dropping it unfinished
            if owned is not None:
                owned.finish(status=type(e).__name__)
            raise
        fut.add_done_callback(
            lambda f, t=tenant, c=cost: self._note_resolved(t, c))
        if owned is not None:
            fut.add_done_callback(_tracing.finish_on_done(owned))
        self._stats.inc("requests")
        _metrics.cached_counter(
            "serve_gen_tier_requests_total", labels={"tier": priority},
            help="generation requests accepted, by priority tier").inc()
        if tenant is not None:
            _metrics.cached_counter(
                "serve_gen_tenant_requests_total",
                labels={"tenant": tenant},
                help="generation requests accepted, by tenant").inc()
        return fut

    def _note_resolved(self, tenant, cost):
        with self._submit_lock:
            self._inflight -= 1
            if tenant is not None:
                left = self._tenant_tokens.get(tenant, 0) - cost
                if left > 0:
                    self._tenant_tokens[tenant] = left
                else:
                    self._tenant_tokens.pop(tenant, None)
            self._g_inflight.set(self._inflight)

    def alive(self):
        """Liveness witness (the front door's /healthz reads it)."""
        with self._submit_lock:
            closed = self._closed
        return not closed and self._thread.is_alive()

    def stats(self):
        out = self._stats.as_dict()
        with self._stats_lock:
            out["max_active"] = self._max_active_seen
            out["cache_hwm"] = dict(self._cache_hwm)
        with self._submit_lock:
            out["inflight"] = self._inflight
            out["tenant_tokens"] = dict(self._tenant_tokens)
        out["max_inflight"] = self._max_inflight
        out["tenant_quotas"] = dict(self._tenant_quotas)
        out["models"] = {m: st.describe()
                         for m, st in dict(self._states).items()}
        # the KV memory claims as measurable evidence (the PR-12
        # weight_bytes discipline): dtype-aware cache/pool BYTES per
        # model — int8 pools count codes + scale pools together
        out["cache_state"] = {
            m: {k: d[k] for k in ("cache_dtype", "cache_mb",
                                  "pool_bytes", "pool_bytes_used",
                                  "pool_bytes_per_token", "block_bytes",
                                  "cache_bytes_per_slot",
                                  "cache_bytes_per_active_seq",
                                  "draft_pool_bytes") if k in d}
            for m, d in out["models"].items()}
        return out

    def close(self, drain=True, timeout=120.0):
        """Stop the engine.  ``drain=True`` (default) runs every
        admitted AND queued generation to completion first —
        kill-the-server-under-load keeps its promises; ``drain=False``
        fails queued and in-flight work fast with ServeClosed.
        Idempotent; joins the engine thread."""
        with self._submit_lock:
            if not self._closed:
                self._closed = True
                self._drain_on_stop = bool(drain)
                self._queue.put(_STOP)
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise MXNetError("generation engine thread failed to stop "
                             "within %.0fs" % timeout)
        self._completer.close(timeout)
        # retire this engine's labeled series from the process scrape
        _metrics.drop(self._mlabels)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- engine thread -------------------------------------------------
    def _serve_loop(self):
        try:
            stopping = False
            while True:
                stopping = self._pump(stopping) or stopping
                if stopping and not self._drain_on_stop:
                    self._fail_all()
                    return
                self._admit_ready()
                self._decode_tick()
                if stopping and not self._has_work():
                    return
        finally:
            # same exit contract as the forward engine: the loop is
            # gone (clean close OR crash), so latch closed and fail
            # anything still queued/waiting/in-flight — an accepted
            # request is never silently dropped.  A crash additionally
            # dumps the flight ring as a postmortem naming the failure.
            exc = sys.exc_info()[1]
            if exc is not None:
                fl = _tracing.flight()
                fl.record("crash", "generation engine loop",
                          error=repr(exc))
                fl.dump(reason="generation engine loop crashed: %r"
                        % (exc,))
            with self._submit_lock:
                self._closed = True
            while True:
                try:
                    item = self._queue.get_nowait()
                except queue.Empty:
                    break
                if item is not _STOP:
                    self._fail_request(item, self._closed_exc(
                        "generation engine dispatch loop exited before "
                        "this request could be served"))
            self._fail_all()

    def _has_work(self):
        if any(self._waiting.values()):
            return True
        return any(st.active() for st in self._states.values())

    def _pump(self, stopping):
        """Move queued requests into the per-model FIFO waiting deques.
        Blocks only when the engine is otherwise idle (close() unblocks
        via the _STOP sentinel).  Returns True when _STOP was seen."""
        stop_seen = False
        block = not stopping and not self._has_work()
        while True:
            try:
                item = self._queue.get() if block \
                    else self._queue.get_nowait()
            except queue.Empty:
                break
            block = False
            if item is _STOP:
                stop_seen = True
                continue
            dq = self._waiting.setdefault(item.model,
                                          collections.deque())
            if item.priority == TIERS[0]:
                # each waiting deque is kept [latency..., batch...]:
                # a latency arrival admits before every parked batch
                # request (after older latency ones — FIFO holds
                # within a tier)
                pos = len(dq)
                for i, parked in enumerate(dq):
                    if parked.priority != TIERS[0]:
                        pos = i
                        break
                dq.insert(pos, item)
            else:
                dq.append(item)
        return stop_seen

    # -- admission (prefill) -------------------------------------------
    def _admit_ready(self):
        for model in list(self._waiting):
            dq = self._waiting.get(model)
            if dq:
                self._admit_model(model, dq)
            if not self._waiting.get(model):
                self._waiting.pop(model, None)

    def _admit_model(self, model, dq):
        try:
            store = self._registry.gen_store(model)
        except MXNetError as e:  # model removed after submit
            while dq:
                self._fail_request(dq.popleft(), e)
            return
        if getattr(store, "paged", False):
            self._admit_paged(model, dq, store)
            return
        st = self._states.get(model)
        cap = store.max_slots()
        if self._max_active is not None:
            cap = min(cap, self._max_active)
        active = len(st.active()) if st else 0
        free = cap - active
        group = []
        now = time.monotonic()
        while dq and len(group) < free:
            r = dq.popleft()
            if r.deadline is not None and now > r.deadline:
                self._fail_request(r, ServeTimeout(
                    "generation request for %r timed out after %.1f ms "
                    "in queue" % (model, (now - r.t_submit) * 1e3)),
                    kind="timeouts")
            elif r.future.set_running_or_notify_cancel():
                group.append(r)
            else:
                self._stats.inc("cancelled")
        if not group:
            return
        toks, lens = store.pad_prompts([r.prompt for r in group])
        try:
            # one prefill serves the whole admitted group: its span
            # lands in every member's trace
            with _tracing.activate_many(
                    [(r.trace, r.trace_parent) for r in group]):
                first_logits, pk, pv = self._dispatch_prefill(
                    store, toks, lens)
            logits = np.asarray(first_logits)
        except BaseException as e:  # noqa: BLE001 — forwarded to futures
            exc = e if isinstance(e, MXNetError) \
                else MXNetError("prefill dispatch failed: %r" % (e,))
            _tracing.flight().record(
                "error", "prefill_dispatch_failed", model=model,
                error=repr(e), requests=len(group))
            for r in group:
                self._fail_request(r, exc, running=True)
            return
        self._stats.inc("prefills")
        self._stats.inc("prefill_seqs", len(group))
        # first generated token (the TTFT moment): one shared-sampler
        # call over the FULL prefill bucket's rows (pad rows sample
        # junk harmlessly — constant shapes mean the jitted sampler
        # compiles once per batch bucket, never inside steady-state
        # admissions) with each request's INITIAL key; the carry keys
        # seed the per-slot chains, so decode steps — in-graph or
        # host — continue the same deterministic stream
        from .program_store import host_sample
        bb = logits.shape[0]
        keys0 = np.zeros((bb, 2), np.uint32)
        temps0 = np.zeros((bb,), np.float32)
        tks0 = np.zeros((bb,), np.int32)
        for i, r in enumerate(group):
            keys0[i] = np.asarray(jax.random.PRNGKey(r.seed))
            temps0[i] = r.temperature
            tks0[i] = r.top_k
        first_toks, carry = host_sample(logits, keys0, temps0, tks0)
        first_toks = np.asarray(first_toks)
        carry = np.asarray(carry)
        survivors = []
        for i, r in enumerate(group):
            self._admit_log.append((model, r.seq))
            tok = int(first_toks[i])
            self._push_token(r, tok)
            if self._finished_reason(r, tok):
                self._finish(r, self._finished_reason(r, tok))
            else:
                survivors.append((i, r))
        if not survivors:
            return
        if st is None:
            st = self._states[model] = _ModelState(store)
            store.cache_state = st
        need = len(st.active()) + len(survivors)
        if need > len(st.slots):
            self._grow_slots(st, store, store.batch_bucket(need))
        Cp = int(pk.shape[3])
        if st.cache_k is None:
            st.cache_k, st.cache_v = store.new_cache(len(st.slots), Cp)
            st.C = Cp
        elif Cp > st.C:
            self._grow_cache(st, store.kv_bucket(Cp))
        # np.array COPIES: asarray of a jax array is a read-only view
        slot_keys = np.array(st.keys, np.uint32)
        for i, r in survivors:
            slot = st.free_slot()
            self._admit_row(st, pk, pv, i, slot)
            st.slots[slot] = r
            st.lengths[slot] = len(r.prompt)
            st.next_tok[slot] = r.tokens[-1]
            st.temps[slot] = r.temperature
            st.top_ks[slot] = r.top_k
            slot_keys[slot] = carry[i]
        st.keys = jnp.asarray(slot_keys)
        self._note_cache_hwm(model, st)
        with self._stats_lock:
            if len(st.active()) > self._max_active_seen:
                self._max_active_seen = len(st.active())

    def _note_cache_hwm(self, model, st):
        d = st.describe()
        with self._stats_lock:
            prev = self._cache_hwm.get(model)
            if prev is None or d.get("cache_mb", 0.0) >= \
                    prev.get("cache_mb", 0.0):
                self._cache_hwm[model] = d

    def _admit_row(self, st, pk, pv, row, slot):
        """Copy one prefilled sequence's cache rows into a decode slot
        (device-side; the batch cache is consumed and rebound)."""
        key = (tuple(pk.shape), tuple(st.cache_k.shape))
        fn = self._admit_fns.get(key)
        if fn is None:
            Cp, C = int(pk.shape[3]), int(st.cache_k.shape[3])

            def f(ck, cv, pk_, pv_, slot_, row_):
                rk = jax.lax.dynamic_slice_in_dim(pk_, row_, 1, 1)
                rv = jax.lax.dynamic_slice_in_dim(pv_, row_, 1, 1)
                pad = ((0, 0), (0, 0), (0, 0), (0, C - Cp), (0, 0))
                rk = jnp.pad(rk, pad)
                rv = jnp.pad(rv, pad)
                ck = jax.lax.dynamic_update_slice(
                    ck, rk, (0, slot_, 0, 0, 0))
                cv = jax.lax.dynamic_update_slice(
                    cv, rv, (0, slot_, 0, 0, 0))
                return ck, cv

            from .program_store import cache_donate_argnums
            fn = jax.jit(f, donate_argnums=cache_donate_argnums((0, 1)))
            self._admit_fns[key] = fn
        st.cache_k, st.cache_v = fn(st.cache_k, st.cache_v, pk, pv,
                                    np.int32(slot), np.int32(row))

    def _grow_slots(self, st, store, new_bb):
        grow = new_bb - len(st.slots)
        st.slots.extend([None] * grow)
        st.lengths = np.concatenate(
            [st.lengths, np.zeros(grow, np.int32)])
        st.next_tok = np.concatenate(
            [st.next_tok, np.zeros(grow, np.int32)])
        st.temps = np.concatenate(
            [st.temps, np.zeros(grow, np.float32)])
        st.top_ks = np.concatenate(
            [st.top_ks, np.zeros(grow, np.int32)])
        st.keys = jnp.concatenate(
            [st.keys, jnp.zeros((grow, 2), jnp.uint32)])
        if st.cache_k is not None:
            pad = ((0, 0), (0, grow), (0, 0), (0, 0), (0, 0))
            st.cache_k = jnp.pad(st.cache_k, pad)
            st.cache_v = jnp.pad(st.cache_v, pad)
        self._stats.inc("slot_grows")

    def _grow_cache(self, st, new_c):
        pad = ((0, 0), (0, 0), (0, 0), (0, new_c - st.C), (0, 0))
        st.cache_k = jnp.pad(st.cache_k, pad)
        st.cache_v = jnp.pad(st.cache_v, pad)
        st.C = new_c
        self._stats.inc("cache_grows")
        self._note_cache_hwm(st.store.name, st)

    # -- paged plane ---------------------------------------------------
    def _paged_state(self, model, store):
        st = self._states.get(model)
        if st is None:
            # speculative decoding gate, resolved ONCE at state
            # creation: a draft attached via registry.add_draft_model
            # + in-graph sampling + MXNET_SERVE_SPEC != 0.  Attach
            # drafts before the model's first request — a draft added
            # under traffic is picked up at the next engine (or the
            # next state, once the engine restarts).
            draft, spec_k = None, 0
            spec = str(get_env("MXNET_SERVE_SPEC") or "auto").lower()
            if spec not in ("0", "off", "false") \
                    and store.sample_mode == "graph":
                draft = getattr(self._registry, "draft_store",
                                lambda _m: None)(model)
                if draft is not None:
                    # the window the draft's verify programs were
                    # warmed for (add_draft_model's spec_k)
                    spec_k = int(getattr(
                        draft, "spec_k",
                        int(get_env("MXNET_SERVE_SPEC_K"))))
            st = self._states[model] = _PagedModelState(
                store, draft=draft, spec_k=spec_k)
            if draft is not None:
                # auto (default) degrades to plain decode when the
                # rolling acceptance collapses; on/force always drafts
                st.spec_forced = spec in ("1", "on", "force", "always")
            store.cache_state = st
            lbl = dict(self._mlabels, model=model)
            st.g_used = _metrics.gauge(
                "serve_kv_pool_blocks_used", labels=lbl,
                help="paged KV pool blocks currently allocated")
            st.g_hwm = _metrics.gauge(
                "serve_kv_pool_blocks_hwm", labels=lbl,
                help="paged KV pool allocation high-water mark")
            st.g_bytes = _metrics.gauge(
                "serve_kv_pool_bytes_used", labels=lbl,
                help="dtype-aware bytes backing the allocated paged "
                     "KV pool blocks (int8 counts codes + scales)")
        return st

    def _paged_gauges(self, st):
        st.g_used.set(st.pool.used())
        st.g_hwm.set(st.pool.hwm)
        st.g_bytes.set(st.pool.used() * st.describe()["block_bytes"])

    def _paged_alloc(self, st):
        """One fresh pool block, evicting LRU prefix pins if the free
        list is dry.  Exhaustion raises — admission reservations exist
        to make that unreachable."""
        b = st.pool.alloc()
        while b is None and st.prefix.evict_one():
            b = st.pool.alloc()
        if b is None:
            raise MXNetError(
                "paged KV pool exhausted (%d blocks) — admission "
                "reservations should have prevented this"
                % st.pool.capacity())
        return b

    def _admit_paged(self, model, dq, store):
        """Paged admission: no prefill dispatch here — a slot is
        claimed, its block table seeded from the prefix cache (shared
        blocks adopted at +1 refcount each), and the prompt's
        remaining tokens left for the tick loop to chunk through.
        FIFO, never overtaking: the head request waiting on pool
        space blocks everyone behind it."""
        st = self._paged_state(model, store)
        bs = store.kv_block
        cap = store.max_slots()
        if self._max_active is not None:
            cap = min(cap, self._max_active)
        admitted = 0
        while dq:
            now = time.monotonic()
            r = dq[0]
            if r.deadline is not None and now > r.deadline:
                dq.popleft()
                self._fail_request(r, ServeTimeout(
                    "generation request for %r timed out after %.1f ms "
                    "in queue" % (model, (now - r.t_submit) * 1e3)),
                    kind="timeouts")
                continue
            if len(st.active()) >= cap:
                break
            total_blocks = -(-(len(r.prompt) + r.max_tokens) // bs)
            blocks, tail = st.prefix.match(r.prompt)
            # a partially-filled last prompt block gets pinned by the
            # prefix cache at registration, so the first decode write
            # into it MUST copy-on-write-fork — one allocation past
            # total_blocks.  A tail HIT already counts its fork target
            # in total_blocks (the borrowed block is free).
            fork_extra = int(len(r.prompt) % bs != 0 and tail is None)
            needed = total_blocks - len(blocks) + fork_extra
            if total_blocks + fork_extra > st.pool.capacity():
                # can never fit, even against an empty pool: shed
                dq.popleft()
                self._stats.inc("shed_pool")
                self._stats.inc("shed")
                self._fail_request(r, ServeOverloaded(
                    "request needs %d KV blocks, past the paged "
                    "pool's %d usable blocks — shed"
                    % (total_blocks + fork_extra, st.pool.capacity())))
                continue
            budget = (st.pool.free_count() + st.prefix.evictable() -
                      st.reserved_total())
            if needed > budget:
                break   # wait for retirements; no overtaking
            dq.popleft()
            if not r.future.set_running_or_notify_cancel():
                self._stats.inc("cancelled")
                continue
            slot = st.free_slot()
            if slot is None:
                need = len(st.active()) + 1
                self._grow_paged_slots(st, store,
                                       store.batch_bucket(need))
                slot = st.free_slot()
            row = st.tables[slot]
            row[:] = 0
            for j, b in enumerate(blocks):
                row[j] = b
                st.pool.ref(b)
            covered = len(blocks) * bs
            if tail is not None:
                row[len(blocks)] = tail
                st.pool.ref(tail)
                covered = len(r.prompt)
            if covered:
                self._stats.inc("prefix_hits")
                self._stats.inc("prefix_hit_blocks",
                                len(blocks) + (tail is not None))
                self._stats.inc("prefix_hit_tokens", covered)
                _metrics.cached_counter(
                    "serve_prefix_hit_total",
                    help="admissions that reused shared paged-KV "
                         "prefix blocks").inc()
            # shared tokens skip recomputation, but the LAST prompt
            # token always reruns: its logits seed the first sample
            prog = min(covered, len(r.prompt) - 1)
            st.prog[slot] = prog
            st.lengths[slot] = prog
            st.decoding[slot] = False
            st.chunks_done[slot] = 0
            st.slots[slot] = r
            st.next_tok[slot] = 0
            st.temps[slot] = r.temperature
            st.top_ks[slot] = r.top_k
            st.resv[slot] = needed
            keys = np.array(st.keys, np.uint32)
            if 0 <= r.seed < 2 ** 32:
                # byte-identical to jax.random.PRNGKey(seed) for
                # 32-bit seeds, without paying a threefry dispatch
                # on the admission hot path
                keys[slot] = (0, r.seed)
            else:
                keys[slot] = np.asarray(jax.random.PRNGKey(r.seed))
            st.keys = jnp.asarray(keys)
            if st.draft is not None:
                # the draft's KV frontier starts at the shared-prefix
                # coverage like the target's (its pool was mirrored
                # when those blocks were first prefilled), and its
                # PRNG chain is an independent fold of the request
                # seed — target and draft draws never correlate.
                # While the auto-mode fallback has the mirror off, the
                # adopted blocks' draft rows are unwritten: claim NO
                # coverage so a probe's catch-up rebuilds from the
                # prompt instead of trusting garbage
                st.dlen[slot] = prog if st.spec_mirror() else 0
                # salted threefry key derived on HOST: the draft's
                # constant hi word can never equal a target key's, so
                # the chains stay decorrelated — the jax.random
                # fold_in this replaces cost a threefry dispatch plus
                # a device round-trip PER ADMISSION, charged even
                # while the fallback regime never drafts at all
                st.dkeys[slot] = (
                    np.uint32(0x5bec5bec),
                    np.uint32(r.seed & 0xffffffff)
                    ^ np.uint32(0x9e3779b9))
            self._admit_log.append((model, r.seq))
            admitted += 1
        if admitted:
            self._stats.inc("prefill_seqs", admitted)
            self._note_cache_hwm(model, st)
            with self._stats_lock:
                if len(st.active()) > self._max_active_seen:
                    self._max_active_seen = len(st.active())
        self._paged_gauges(st)

    def _grow_paged_slots(self, st, store, new_bb):
        grow = new_bb - len(st.slots)
        st.slots.extend([None] * grow)
        st.tables = np.concatenate(
            [st.tables, np.zeros((grow, st.tb), np.int32)])
        for name in ("lengths", "prog", "chunks_done", "next_tok",
                     "top_ks", "resv"):
            arr = getattr(st, name)
            setattr(st, name, np.concatenate(
                [arr, np.zeros(grow, arr.dtype)]))
        st.decoding = np.concatenate(
            [st.decoding, np.zeros(grow, bool)])
        st.temps = np.concatenate(
            [st.temps, np.zeros(grow, np.float32)])
        st.keys = jnp.concatenate(
            [st.keys, jnp.zeros((grow, 2), jnp.uint32)])
        if st.draft is not None:
            st.dlen = np.concatenate(
                [st.dlen, np.zeros(grow, np.int32)])
            st.dkeys = np.concatenate(
                [np.array(st.dkeys, np.uint32),
                 np.zeros((grow, 2), np.uint32)])
        self._stats.inc("slot_grows")

    def _release_paged_slot(self, st, i):
        """Drop slot i's block references and bookkeeping (retire and
        failure paths; the prefix cache's pins keep shared blocks
        alive past this)."""
        for j in range(st.tb):
            b = int(st.tables[i, j])
            if b:
                st.pool.deref(b)
        st.tables[i, :] = 0
        st.slots[i] = None
        st.lengths[i] = 0
        st.prog[i] = 0
        st.decoding[i] = False
        st.chunks_done[i] = 0
        st.next_tok[i] = 0
        st.temps[i] = 0.0
        st.top_ks[i] = 0
        st.resv[i] = 0
        if st.draft is not None:
            st.dlen[i] = 0

    def _paged_tick(self, model, st):
        """One engine tick of the paged plane: ONE decode step for the
        generating slots, then ONE prompt chunk for the prefilling
        slots — long prompts advance prefill_chunk tokens per tick
        INTERLEAVED with everyone else's decode steps, so a long
        prefill stops spiking co-running streams' inter-token
        latency."""
        dec = [i for i in st.active() if st.decoding[i]]
        if dec:
            if st.draft is not None and self._spec_active(st):
                self._paged_spec_step(model, st, dec)
            else:
                self._paged_decode_step(model, st, dec)
        pre = [i for i in st.active() if not st.decoding[i]]
        if pre:
            self._paged_prefill_chunk(model, st, pre)
        if dec or pre:
            self._paged_gauges(st)

    def _paged_write_ready(self, st, i, positions):
        """Make slot i's table writable at ``positions``: allocate
        entries still at 0 and copy-on-write-fork any covering block
        someone else also references (refcount > 1 — a shared prefix
        tail, or a block pinned by the prefix cache).  Generation
        writes past the registered prompt MUST fork; recomputed prompt
        positions rewrite shared blocks with bit-identical values, so
        they are exempted by callers passing only new positions."""
        bs = st.store.kv_block
        for j in sorted({p // bs for p in positions}):
            b = int(st.tables[i, j])
            if b == 0:
                st.tables[i, j] = self._paged_alloc(st)
                st.resv[i] = max(0, int(st.resv[i]) - 1)
            elif st.pool.refcount(b) > 1:
                nb = self._paged_alloc(st)
                if st.scales is None:
                    st.pool_k, st.pool_v = st.store.copy_block(
                        st.pool_k, st.pool_v, b, nb)
                else:
                    # int8: codes and per-block scales fork together
                    st.pool_k, st.pool_v, sk, sv = st.store.copy_block(
                        st.pool_k, st.pool_v, b, nb, scales=st.scales)
                    st.scales = (sk, sv)
                if st.draft is not None:
                    # the draft plane shares the block TABLES, so its
                    # pool must fork the same physical block
                    if st.dscales is None:
                        st.dpool_k, st.dpool_v = st.draft.copy_block(
                            st.dpool_k, st.dpool_v, b, nb)
                    else:
                        (st.dpool_k, st.dpool_v, dsk,
                         dsv) = st.draft.copy_block(
                            st.dpool_k, st.dpool_v, b, nb,
                            scales=st.dscales)
                        st.dscales = (dsk, dsv)
                st.pool.deref(b)
                st.tables[i, j] = nb
                st.resv[i] = max(0, int(st.resv[i]) - 1)
                self._stats.inc("cow_forks")

    def _paged_dispatch(self, st, tables, toks, pos, val, do, phase):
        """One unified paged step (decode OR prompt chunk — ``phase``
        names it for the profiler/traces) + one sampled token per
        ``do`` row, host-side np result.  Same graph/host sampling
        split as the contiguous plane's ``_decode_and_sample``."""
        if st.store.sample_mode == "graph":
            t0 = time.perf_counter_ns()
            out = st.store.run_paged_step_sample(
                st.pool_k, st.pool_v, tables, toks, pos, val,
                st.keys, st.temps, st.top_ks, do, scales=st.scales)
            if st.scales is None:
                toks_dev, st.pool_k, st.pool_v, st.keys = out
            else:
                toks_dev, st.pool_k, st.pool_v, sk, sv, st.keys = out
                st.scales = (sk, sv)
            _profiler.record_phase(phase, t0)
            t0 = time.perf_counter_ns()
            sampled = self._fetch_decode(toks_dev)
            _profiler.record_phase("serve_sample", t0)
            return sampled
        t0 = time.perf_counter_ns()
        out = st.store.run_paged_step(
            st.pool_k, st.pool_v, tables, toks, pos, val,
            scales=st.scales)
        if st.scales is None:
            logits_dev, st.pool_k, st.pool_v = out
        else:
            logits_dev, st.pool_k, st.pool_v, sk, sv = out
            st.scales = (sk, sv)
        _profiler.record_phase(phase, t0)
        t0 = time.perf_counter_ns()
        logits = self._fetch_decode(logits_dev)
        from .program_store import host_sample
        toks_out, carry = host_sample(logits, st.keys, st.temps,
                                      st.top_ks)
        st.keys = jnp.where(jnp.asarray(do)[:, None], carry, st.keys)
        sampled = np.asarray(toks_out)
        _profiler.record_phase("serve_sample", t0)
        return sampled

    def _paged_decode_step(self, model, st, dec):
        """Advance every generating slot one token (serve_decode
        phase).  Slots mid-prefill (and empty slots) ride the dispatch
        with all-zero tables — their writes land in the trash block
        and their outputs are discarded."""
        for i in dec:
            # the write position this step: COW-fork or allocate first
            self._paged_write_ready(st, i, [int(st.lengths[i])])
        n = len(st.slots)
        tables = np.zeros((n, st.tb), np.int32)
        toks = np.zeros((n, 1), np.int32)
        pos = np.zeros((n,), np.int32)
        val = np.ones((n,), np.int32)
        do = np.zeros((n,), bool)
        for i in dec:
            tables[i] = st.tables[i]
            toks[i, 0] = st.next_tok[i]
            pos[i] = st.lengths[i]
            do[i] = True
        try:
            with _tracing.activate_many(
                    [(st.slots[i].trace, st.slots[i].trace_parent)
                     for i in dec]):
                sampled = self._paged_dispatch(st, tables, toks, pos,
                                               val, do, "serve_decode")
        except BaseException as e:  # noqa: BLE001 — to the futures
            exc = e if isinstance(e, MXNetError) \
                else MXNetError("decode dispatch failed: %r" % (e,))
            _tracing.flight().record(
                "error", "decode_dispatch_failed", model=model,
                error=repr(e), slots=len(dec))
            for i in dec:
                r = st.slots[i]
                self._release_paged_slot(st, i)
                self._fail_request(r, exc, running=True)
            return
        for i in dec:
            r = st.slots[i]
            st.lengths[i] += 1
            tok = int(sampled[i])
            self._push_token(r, tok)
            st.next_tok[i] = tok
            reason = self._finished_reason(r, tok)
            if reason:
                self._release_paged_slot(st, i)
                self._finish(r, reason)
        self._stats.inc("decode_steps")
        self._stats.inc("generated_tokens", len(dec))

    def _spec_active(self, st):
        """The MXNET_SERVE_SPEC=auto degradation gate, checked once
        per tick: speculate while the rolling acceptance EMA holds,
        otherwise serve plain decode steps (identical token streams —
        greedy is byte-identical either way, seeded draws stay
        distribution-identical) and probe a speculative tick on an
        exponential-backoff cadence to notice recovery."""
        if st.spec_forced or st.spec_ema >= _SPEC_EMA_FLOOR:
            st.spec_probe_every = _SPEC_PROBE_EVERY
            st.spec_probe = _SPEC_PROBE_EVERY
            return True
        st.spec_probe -= 1
        if st.spec_probe <= 0:
            # this probe's verdict lands in the EMA before the next
            # tick re-checks the gate: a recovered draft re-engages
            # (and resets the cadence above), a still-hostile one
            # waits twice as long for the next probe
            st.spec_probe_every = min(2 * st.spec_probe_every,
                                      _SPEC_PROBE_MAX)
            st.spec_probe = st.spec_probe_every
            return True
        self._stats.inc("spec_fallback_steps")
        return False

    def _spec_catch_up(self, st, dec, gap):
        """Teacher-forced chunked catch-up of the draft KV frontier:
        after fallback ticks (or a mid-stream draft lag > 1) the gap
        between the target's frontier and the draft's can span many
        tokens — replaying them one micro-step each would cost a draft
        dispatch per skipped token.  The tokens are all KNOWN (already
        emitted), so feed them through the draft's logits-discarded
        prefill-mirror program in ``prefill_chunk``-sized dispatches
        (per-row ``valid`` masks ragged gaps), exactly like the prompt
        mirror.  Leaves every slot at gap 0."""
        draft = st.draft
        n = len(st.slots)
        chunk = draft.prefill_chunk
        done = 0
        maxgap = max(gap[i] for i in dec)
        while done < maxgap:
            tables = np.zeros((n, st.tb), np.int32)
            toks = np.zeros((n, chunk), np.int32)
            pos = np.zeros((n,), np.int32)
            val = np.ones((n,), np.int32)
            for i in dec:
                rem = gap[i] - done
                if rem <= 0:
                    continue
                r = st.slots[i]
                take = min(chunk, rem)
                base = int(st.dlen[i]) + done
                plen = len(r.prompt)
                for c in range(take):
                    # a lazily-mirrored slot catches up from inside
                    # its prompt; past plen the replay is the emitted
                    # stream (idx L-1 at most — index len(tokens)-2)
                    idx = base + c
                    toks[i, c] = (r.prompt[idx] if idx < plen
                                  else r.tokens[idx - plen])
                tables[i] = st.tables[i]
                pos[i] = base
                val[i] = take
            dout = draft.run_paged_step(
                st.dpool_k, st.dpool_v, tables, toks, pos, val,
                scales=st.dscales)
            if st.dscales is None:
                _, st.dpool_k, st.dpool_v = dout
            else:
                _, st.dpool_k, st.dpool_v, dsk, dsv = dout
                st.dscales = (dsk, dsv)
            self._stats.inc("spec_draft_steps")
            done += chunk
        for i in dec:
            st.dlen[i] += gap[i]
            gap[i] = 0

    def _spec_propose(self, st, dec, win):
        """Draft micro-steps of one speculative tick: first catch each
        slot's draft KV frontier up to the target's (re-feeding
        already-emitted tokens with ``do_sample`` off — the draft's
        PRNG chain must not advance on catch-up rows), then sample
        ``win[i]`` proposal tokens.  Returns ``(props, prop_q)``:
        per-slot proposal token lists and the DEVICE-resident
        ``(slots, K, vocab)`` proposal distributions the verify
        program consumes — distributions never cross to the host."""
        draft = st.draft
        n = len(st.slots)
        K = st.spec_k
        plen = {i: len(st.slots[i].prompt) for i in dec}
        gap = {i: int(st.lengths[i]) - int(st.dlen[i]) for i in dec}
        if max(gap.values()) > 1:
            # a fallback stretch left the draft far behind: chunked
            # teacher-forced catch-up instead of one micro-step per
            # skipped token (gap stays <= 1 in steady speculation —
            # exactly the full-accept bonus token)
            self._spec_catch_up(st, dec, gap)
        steps = {i: gap[i] + win[i] for i in dec}
        total = max(steps.values())
        props = {i: [] for i in dec}
        q_rows = []
        for t in range(total):
            tables = np.zeros((n, st.tb), np.int32)
            toks = np.zeros((n, 1), np.int32)
            pos = np.zeros((n,), np.int32)
            val = np.ones((n,), np.int32)
            do = np.zeros((n,), bool)
            live = []
            for i in dec:
                if t >= steps[i]:
                    continue
                r = st.slots[i]
                idx = int(st.dlen[i]) + t  # token index fed this step
                L = int(st.lengths[i])
                if idx < plen[i]:
                    # inside the prompt: a lazily-mirrored slot's
                    # catch-up (mirror skipped during fallback)
                    tok = r.prompt[idx]
                elif idx <= L:
                    # emitted history (idx == L is next_tok: the last
                    # emitted token, r.tokens[-1])
                    tok = r.tokens[idx - plen[i]]
                else:
                    tok = props[i][idx - L - 1]
                tables[i] = st.tables[i]
                toks[i, 0] = tok
                pos[i] = idx
                do[i] = t >= gap[i]
                live.append(i)
            out = draft.run_paged_step_sample_p(
                st.dpool_k, st.dpool_v, tables, toks, pos, val,
                st.dkeys, st.temps, st.top_ks, do, scales=st.dscales)
            if st.dscales is None:
                t_dev, q_dev, st.dpool_k, st.dpool_v, st.dkeys = out
            else:
                (t_dev, q_dev, st.dpool_k, st.dpool_v, dsk, dsv,
                 st.dkeys) = out
                st.dscales = (dsk, dsv)
            sampled = self._fetch_decode(t_dev)
            q_rows.append(q_dev)
            for i in live:
                if t >= gap[i]:
                    props[i].append(int(sampled[i]))
            self._stats.inc("spec_draft_steps", len(live))
        # the sampler returned advanced keys on device; pull them
        # back (np.array: asarray of a device buffer is read-only)
        # so admissions between spec ticks stay numpy-only
        st.dkeys = np.array(st.dkeys, np.uint32)
        for i in dec:
            st.dlen[i] += steps[i]   # draft frontier = L + win[i]
        if not q_rows:
            return props, jnp.zeros(
                (n, K, draft._spec["vocab_size"]), jnp.float32)
        # device-side gather: slot i's K proposal distributions are
        # micro-steps gap[i]..gap[i]+win[i]-1 (rows past win[i] are
        # clamped garbage the verify's per-slot `valid` masks off)
        qs = jnp.stack(q_rows, axis=1)          # (n, S, vocab)
        g = np.zeros((n,), np.int32)
        for i in dec:
            g[i] = gap[i]
        idx = np.minimum(
            g[:, None] + np.arange(K, dtype=np.int32)[None, :],
            len(q_rows) - 1)
        return props, qs[np.arange(n)[:, None], idx]

    def _paged_spec_step(self, model, st, dec):
        """One speculative decode tick: the draft proposes up to
        ``spec_k`` tokens per generating slot, the target verifies all
        K+1 positions in ONE dispatch with the accept/reject rule
        in-graph, and each slot emits 1..K+1 tokens.  Rejected
        proposals roll back by table arithmetic alone — ``lengths``
        just doesn't advance past the emitted count, and pool rows
        beyond the frontier are junk the paged kernels never read
        (rewritten by later steps; no pool copies)."""
        K = st.spec_k
        win = {}
        for i in dec:
            r = st.slots[i]
            # never propose past the request's budget: the verify step
            # emits at most remaining tokens (window + bonus)
            win[i] = max(0, min(K, r.max_tokens - len(r.tokens) - 1))
            L = int(st.lengths[i])
            # the verify writes positions L..L+W: COW-fork or allocate
            # first (the draft micro-steps write the same blocks)
            self._paged_write_ready(st, i,
                                    list(range(L, L + win[i] + 1)))
        n = len(st.slots)
        try:
            with _tracing.activate_many(
                    [(st.slots[i].trace, st.slots[i].trace_parent)
                     for i in dec]):
                props, prop_q = self._spec_propose(st, dec, win)
                tables = np.zeros((n, st.tb), np.int32)
                vtoks = np.zeros((n, K + 1), np.int32)
                pos = np.zeros((n,), np.int32)
                val = np.ones((n,), np.int32)
                do = np.zeros((n,), bool)
                for i in dec:
                    tables[i] = st.tables[i]
                    vtoks[i, 0] = st.next_tok[i]
                    for j, tok in enumerate(props[i]):
                        vtoks[i, 1 + j] = tok
                    pos[i] = st.lengths[i]
                    val[i] = win[i] + 1
                    do[i] = True
                t0 = time.perf_counter_ns()
                out = st.store.run_paged_verify(
                    st.pool_k, st.pool_v, tables, vtoks, pos, val,
                    prop_q, st.keys, st.temps, st.top_ks, do,
                    scales=st.scales)
                if st.scales is None:
                    out_dev, ne_dev, st.pool_k, st.pool_v, \
                        st.keys = out
                else:
                    (out_dev, ne_dev, st.pool_k, st.pool_v, sk, sv,
                     st.keys) = out
                    st.scales = (sk, sv)
                _profiler.record_phase("serve_decode", t0)
                t0 = time.perf_counter_ns()
                out_toks = self._fetch_decode(out_dev)
                n_emit = self._fetch_decode(ne_dev)
                _profiler.record_phase("serve_sample", t0)
        except BaseException as e:  # noqa: BLE001 — to the futures
            exc = e if isinstance(e, MXNetError) \
                else MXNetError("speculative dispatch failed: %r"
                                % (e,))
            _tracing.flight().record(
                "error", "spec_dispatch_failed", model=model,
                error=repr(e), slots=len(dec))
            for i in dec:
                r = st.slots[i]
                self._release_paged_slot(st, i)
                self._fail_request(r, exc, running=True)
            return
        emitted = 0
        proposed = 0
        accepted = 0
        for i in dec:
            r = st.slots[i]
            ne = int(n_emit[i])
            proposed += win[i]
            accepted += ne - 1
            if _metrics.phase_on():
                _H_SPEC.observe(ne)
            for j in range(ne):
                tok = int(out_toks[i, j])
                self._push_token(r, tok)
                st.lengths[i] += 1
                emitted += 1
                st.next_tok[i] = tok
                reason = self._finished_reason(r, tok)
                if reason:
                    # mid-window EOS: the remaining accepted tokens
                    # are discarded with the slot
                    self._release_paged_slot(st, i)
                    self._finish(r, reason)
                    break
            else:
                # draft KV is valid only while its tokens match the
                # accepted stream: clamp to the new frontier after a
                # rejection (full accept leaves a 1-token catch-up gap
                # for the bonus token)
                st.dlen[i] = min(int(st.dlen[i]), int(st.lengths[i]))
        self._stats.inc("decode_steps")
        self._stats.inc("spec_steps")
        self._stats.inc("spec_proposed", proposed)
        self._stats.inc("spec_accepted", accepted)
        self._stats.inc("generated_tokens", emitted)
        if proposed:
            st.spec_ema = (_SPEC_EMA_DECAY * st.spec_ema +
                           (1.0 - _SPEC_EMA_DECAY) *
                           (accepted / proposed))
        _metrics.cached_counter(
            "serve_spec_proposed_total",
            help="draft tokens offered to speculative verify").inc(
                proposed)
        _metrics.cached_counter(
            "serve_spec_accept_total",
            help="draft tokens accepted by speculative verify").inc(
                accepted)

    def _paged_prefill_chunk(self, model, st, pre):
        """Advance every prefilling slot one prompt chunk
        (serve_prefill phase).  Rows finishing their prompt this
        dispatch sample their first token (the TTFT moment), register
        their blocks with the prefix cache and flip to decoding."""
        store = st.store
        bs = store.kv_block
        chunk = store.prefill_chunk
        rows = []
        for i in pre:
            r = st.slots[i]
            p0 = int(st.prog[i])
            ntok = min(chunk, len(r.prompt) - p0)
            # blocks covering NEW positions only: recomputed shared
            # positions rewrite shared blocks with identical values
            # (same tokens, same prefix) and must not fork
            fresh = [p for p in range(p0, p0 + ntok)
                     if st.tables[i, p // bs] == 0]
            self._paged_write_ready(st, i, fresh)
            rows.append((i, r, p0, ntok))
        n = len(st.slots)
        tables = np.zeros((n, st.tb), np.int32)
        toks = np.zeros((n, chunk), np.int32)
        pos = np.zeros((n,), np.int32)
        val = np.ones((n,), np.int32)
        do = np.zeros((n,), bool)
        for i, r, p0, ntok in rows:
            tables[i] = st.tables[i]
            toks[i, :ntok] = r.prompt[p0:p0 + ntok]
            pos[i] = p0
            val[i] = ntok
            do[i] = (p0 + ntok == len(r.prompt))
        try:
            with _tracing.activate_many(
                    [(r.trace, r.trace_parent)
                     for _i, r, _p, _n in rows]):
                sampled = self._paged_dispatch(
                    st, tables, toks, pos, val, do, "serve_prefill")
                if st.draft is not None and st.spec_mirror():
                    # mirror the chunk into the draft's KV plane
                    # (logits unfetched, discarded): same tables, same
                    # tokens — the draft pool ends bit-deterministic
                    # with the prompt, so prefix-shared blocks are
                    # valid draft KV for every adopter.  While the
                    # auto-mode fallback has speculation suspended the
                    # mirror is skipped (zero draft cost per tick); a
                    # probe's catch-up rebuilds the draft KV from the
                    # prompt instead
                    dout = st.draft.run_paged_step(
                        st.dpool_k, st.dpool_v, tables, toks, pos,
                        val, scales=st.dscales)
                    if st.dscales is None:
                        _, st.dpool_k, st.dpool_v = dout
                    else:
                        _, st.dpool_k, st.dpool_v, dsk, dsv = dout
                        st.dscales = (dsk, dsv)
        except BaseException as e:  # noqa: BLE001 — to the futures
            exc = e if isinstance(e, MXNetError) \
                else MXNetError("prefill dispatch failed: %r" % (e,))
            _tracing.flight().record(
                "error", "prefill_dispatch_failed", model=model,
                error=repr(e), requests=len(rows))
            for i, r, _p0, _ntok in rows:
                self._release_paged_slot(st, i)
                self._fail_request(r, exc, running=True)
            return
        self._stats.inc("prefills")
        self._stats.inc("prefill_chunks", len(rows))
        for i, r, p0, ntok in rows:
            st.prog[i] = p0 + ntok
            st.lengths[i] = p0 + ntok
            if st.draft is not None and st.spec_mirror():
                st.dlen[i] = p0 + ntok
            st.chunks_done[i] += 1
            if p0 + ntok < len(r.prompt):
                continue
            if _metrics.phase_on():
                _H_CHUNKS.observe(int(st.chunks_done[i]))
            st.prefix.register(r.prompt, st.tables[i])
            tok = int(sampled[i])
            self._push_token(r, tok)
            reason = self._finished_reason(r, tok)
            if reason:
                self._release_paged_slot(st, i)
                self._finish(r, reason)
            else:
                st.decoding[i] = True
                st.next_tok[i] = tok
        self._note_cache_hwm(model, st)

    # -- decode --------------------------------------------------------
    def _decode_tick(self):
        for model, st in list(self._states.items()):
            if getattr(st, "paged", False):
                self._paged_tick(model, st)
                continue
            act = st.active()
            if not act:
                # batch drained: drop the cache (and its memory) until
                # the next admission starts fresh
                self._states.pop(model)
                st.store.cache_state = None
                continue
            needed = int(st.lengths[act].max()) + 1
            if needed > st.C:
                self._grow_cache(st, st.store.kv_bucket(needed))
            toks = np.ascontiguousarray(st.next_tok)
            lens = np.ascontiguousarray(st.lengths)
            try:
                # one decode step advances every active slot: its
                # serve_decode/serve_sample spans land in each slot's
                # trace
                with _tracing.activate_many(
                        [(st.slots[i].trace, st.slots[i].trace_parent)
                         for i in act]):
                    sampled = self._decode_and_sample(st, toks, lens)
            except BaseException as e:  # noqa: BLE001 — to the futures
                exc = e if isinstance(e, MXNetError) \
                    else MXNetError("decode dispatch failed: %r" % (e,))
                _tracing.flight().record(
                    "error", "decode_dispatch_failed", model=model,
                    error=repr(e), slots=len(act))
                for i in act:
                    r = st.slots[i]
                    st.slots[i] = None
                    self._fail_request(r, exc, running=True)
                continue
            for i in act:
                r = st.slots[i]
                st.lengths[i] += 1
                tok = int(sampled[i])
                self._push_token(r, tok)
                st.next_tok[i] = tok
                reason = self._finished_reason(r, tok)
                if reason:
                    st.slots[i] = None
                    st.lengths[i] = 0
                    st.next_tok[i] = 0
                    st.temps[i] = 0.0
                    st.top_ks[i] = 0
                    self._finish(r, reason)
            self._stats.inc("decode_steps")
            self._stats.inc("generated_tokens", len(act))

    def _decode_and_sample(self, st, toks, lens):
        """One decode step + one token per slot, host-side np result.

        ``graph`` mode dispatches the sampling decode program (tokens
        out; the per-slot PRNG keys are donated alongside the caches
        and rebound) and fetches ONLY the ``(slots,)`` token vector;
        ``host`` mode dispatches the logits program, fetches the whole
        ``(slots, vocab)`` matrix and runs the SAME jitted sampler on
        it.  Either way the fetch + sampling is bracketed by the
        ``serve_sample`` phase and counted in ``decode_fetch_elems``."""
        if st.store.sample_mode == "graph":
            toks_dev = self._dispatch_decode_sample(st, toks, lens)
            t0 = time.perf_counter_ns()
            sampled = self._fetch_decode(toks_dev)
            _profiler.record_phase("serve_sample", t0)
            return sampled
        logits_dev = self._dispatch_decode(st, toks, lens)
        t0 = time.perf_counter_ns()
        logits = self._fetch_decode(logits_dev)
        from .program_store import host_sample
        toks_out, st.keys = host_sample(logits, st.keys, st.temps,
                                        st.top_ks)
        sampled = np.asarray(toks_out)
        _profiler.record_phase("serve_sample", t0)
        return sampled

    def _fetch_decode(self, arr):
        """THE host fetch of the decode loop — one np conversion whose
        element count feeds ``decode_fetch_elems`` (the zero-logits-
        fetch acceptance pin reads it; tests also spy the shapes
        here)."""
        a = np.asarray(arr)
        self._stats.inc("decode_fetch_elems", int(a.size))
        return a

    @hot_path
    def _dispatch_prefill(self, store, tokens, lengths):
        """Enqueue-only prompt-batch dispatch (serve_prefill phase);
        the logits fetch happens on the caller side."""
        t0 = time.perf_counter_ns()
        out = store.run_prefill(tokens, lengths)
        _profiler.record_phase("serve_prefill", t0)
        return out

    @hot_path
    def _dispatch_decode(self, st, tokens, lengths):
        """Enqueue-only logits-out decode dispatch (serve_decode phase;
        the MXNET_SERVE_SAMPLE=host hatch).  The donated caches are
        rebound to the program's outputs before anything can read the
        consumed buffers."""
        t0 = time.perf_counter_ns()
        logits, st.cache_k, st.cache_v = st.store.run_decode(
            st.cache_k, st.cache_v, tokens, lengths)
        _profiler.record_phase("serve_decode", t0)
        return logits

    @hot_path
    def _dispatch_decode_sample(self, st, tokens, lengths):
        """Enqueue-only sampling decode dispatch (serve_decode phase):
        tokens come out sampled in-graph; the donated caches AND the
        per-slot PRNG key state are rebound to the program's outputs."""
        t0 = time.perf_counter_ns()
        toks, st.cache_k, st.cache_v, st.keys = \
            st.store.run_decode_sample(st.cache_k, st.cache_v, tokens,
                                       lengths, st.keys, st.temps,
                                       st.top_ks)
        _profiler.record_phase("serve_decode", t0)
        return toks

    # -- retirement ----------------------------------------------------
    @staticmethod
    def _finished_reason(req, tok):
        if req.eos_id is not None and tok == req.eos_id:
            return "eos"
        if len(req.tokens) >= req.max_tokens:
            return "length"
        return None

    def _push_token(self, req, tok):
        now = time.perf_counter()
        if _metrics.phase_on():
            if not req.token_times:
                _H_TTFT.observe(now - req.t_submit)
            else:
                _H_ITL.observe(now - req.token_times[-1])
        req.tokens.append(tok)
        req.token_times.append(now)
        if req.stream is not None:
            req.stream.push(tok)

    def _finish(self, req, reason):
        if req.stream is not None:
            req.stream.close()
        res = GenerationResult(req.model, len(req.prompt),
                               list(req.tokens), reason, req.t_submit,
                               list(req.token_times))
        self._completer.resolve(req.future, res)
        self._stats.inc("finished")

    def _fail_request(self, req, exc, kind="errors", running=False):
        if not running and not req.future.set_running_or_notify_cancel():
            self._stats.inc("cancelled")
            return
        if req.stream is not None:
            req.stream.close()
        self._completer.resolve(req.future, exc=exc)
        self._stats.inc(kind)

    def _fail_all(self):
        """close(drain=False): everything waiting or in flight fails
        fast — with the owning replica named, so the retry layer and
        the flight recorder see WHICH replica's kill lost the KV
        state."""
        exc = self._closed_exc(
            "generation engine closed before completion")
        for dq in self._waiting.values():
            while dq:
                self._fail_request(dq.popleft(), exc)
        self._waiting.clear()
        for model, st in list(self._states.items()):
            for i in st.active():
                r = st.slots[i]
                st.slots[i] = None
                self._fail_request(r, exc, running=True)
            st.store.cache_state = None
        self._states.clear()

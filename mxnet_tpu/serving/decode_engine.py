"""Autoregressive generation engine: continuous batching on the decode
plane.

The forward batcher (``scheduler.ServingEngine``) amortizes ONE program
dispatch across requests; generation needs the same economics across
*tokens*.  A naive deployment re-runs the full forward for every
generated token (re-paying attention over the whole prefix — the
``serving.decode.reprefill`` bench baseline); this engine runs the
prompt ONCE (prefill, filling the KV cache) and then advances every
in-flight sequence one token per compiled decode step, admitting newly
prefilled sequences into the running batch between steps and retiring
finished ones (EOS / ``max_tokens``) — continuous batching, the regime
where decode throughput stops being per-request and becomes
per-step.

One engine thread owns the loop:

* **pump** — drain the submit queue into per-model FIFO waiting deques
  (blocking only when there is no admitted work at all);
* **admit** — take waiting requests (FIFO, never overtaking — pinned by
  the seeded-loadgen test), run one bucketed prefill batch
  (``serve_prefill`` phase), sample each sequence's first token, and
  copy its cache rows into free decode slots;
* **decode** — one compiled step per model with active slots
  (``serve_decode`` phase): the batch's next-token vector goes in, the
  donated KV cache is updated in place, and — in the default
  ``MXNET_SERVE_SAMPLE=graph`` mode — sampling (greedy, or seeded
  temperature/top-k per request) runs INSIDE the program over per-slot
  PRNG key state that rides as another donated argument, so the only
  per-step host transfer is the ``(slots,)`` token vector.
  ``MXNET_SERVE_SAMPLE=host`` is the escape hatch: the logits-out
  decode program plus the SAME jitted sampler on the host-fetched
  ``(slots, vocab)`` matrix — byte-identical token streams, one big
  fetch per step (``stats()["decode_fetch_elems"]`` counts the
  difference; the profiler's ``serve_sample`` phase brackets it);
* **retire** — a sequence hitting its ``eos_id`` or ``max_tokens``
  resolves its Future with a :class:`GenerationResult` (and closes its
  :class:`TokenStream`, if streaming); its slot frees for the next
  admission.

The KV cache is registry-owned serving state: it lives beside the
params on the model's :class:`~.program_store.GenerativeProgramStore`
(one device-resident copy in the store's ``kv_dtype`` —
``MXNET_SERVE_KV_DTYPE=bfloat16`` halves the bytes per slot;
``stats()`` describes it) and is threaded through the pure decode
programs cache-in/cache-out with donation, so the per-step write is an
in-place ``dynamic_update_slice`` on the resident buffers (donation is
skipped on the CPU backend, matching the training planes' donation
guards).

On the default PAGED plane (``MXNET_SERVE_PAGED=1``) the cache is a
single global pool of ``MXNET_SERVE_KV_BLOCK``-token blocks addressed
through per-slot block tables (:class:`_PagedModelState`): admission
reserves each request's worst-case block need up front (throttling
FIFO when the pool runs short — the pool can never exhaust
mid-flight), completed prefills register their blocks in a
copy-on-write prefix cache (:class:`_PrefixStore` — an identical
prompt prefix adopts the shared blocks instead of re-prefilling;
writes into shared blocks fork first), and prompts prefill in
``MXNET_SERVE_PREFILL_CHUNK``-token chunks AFTER each tick's decode
step so long prompts stop spiking co-running streams' inter-token
latency.  ``paged=False`` (or ``MXNET_SERVE_PAGED=0``) keeps the
contiguous per-slot plane above, bit-identical streams
(docs/architecture/decode_engine.md).

``close(drain=True)`` finishes every admitted AND queued generation
before the thread exits; ``close(drain=False)`` fails everything fast
with :class:`~.scheduler.ServeClosed`.
"""
from __future__ import annotations

import collections
import queue
import sys
import threading
import time
from concurrent.futures import Future

import jax
import jax.numpy as jnp
import numpy as np

from .. import metrics as _metrics
from .. import profiler as _profiler
from .. import tracing as _tracing
from ..analysis.lockcheck import make_lock
from ..base import MXNetError, _uid, get_env, hot_path
from .scheduler import (FutureCompleter, ServeClosed, ServeOverloaded,
                        ServeTimeout, TIERS)

# Aggregate generation histograms (process-wide; gated on
# MXNET_METRICS like every ambient observation seam).  TTFT and ITL
# are THE generation service metrics — the /metrics scrape carries
# their p50/p95/p99 without storing a sample per token.
_H_TTFT = _metrics.histogram(
    "serve_ttft_seconds",
    help="generation time-to-first-token, submit to first sample")
_H_ITL = _metrics.histogram(
    "serve_itl_seconds",
    help="generation inter-token latency, gap between samples")
_H_CHUNKS = _metrics.histogram(
    "serve_prefill_chunks_per_request",
    help="chunked-prefill dispatches one admitted request's prompt "
         "took on the paged decode plane", lo=1, hi=1e4)

__all__ = ["GenerationEngine", "GenerationResult", "TokenStream"]

_STOP = object()


class GenerationResult:
    """One finished generation (what the request's Future resolves to).

    ``tokens`` — the generated ids (prompt excluded); ``finish_reason``
    — ``'eos'`` or ``'length'``; ``token_times`` — host
    ``perf_counter()`` stamps taken as each token was sampled, so
    clients (and the loadgen) derive TTFT (``token_times[0] -
    t_submit``) and inter-token latency without streaming machinery."""

    __slots__ = ("model", "prompt_len", "tokens", "finish_reason",
                 "t_submit", "token_times")

    def __init__(self, model, prompt_len, tokens, finish_reason,
                 t_submit, token_times):
        self.model = model
        self.prompt_len = prompt_len
        self.tokens = tokens
        self.finish_reason = finish_reason
        self.t_submit = t_submit
        self.token_times = token_times

    @property
    def ttft_s(self):
        """Submit -> first generated token (seconds)."""
        return self.token_times[0] - self.t_submit

    def itl_s(self):
        """Inter-token gaps (seconds), one per token after the first."""
        return [b - a for a, b in zip(self.token_times,
                                      self.token_times[1:])]

    def __repr__(self):
        return ("GenerationResult(model=%r, %d tokens, %s)"
                % (self.model, len(self.tokens), self.finish_reason))


class TokenStream:
    """Blocking per-sequence token iterator.

    Construct one and pass it to :meth:`GenerationEngine.submit`
    (``stream=``): the engine pushes each sampled token id as it is
    generated and closes the stream when the sequence retires, so
    ``for tok in stream: ...`` sees tokens at inter-token latency
    instead of waiting for the Future."""

    _CLOSE = object()

    def __init__(self):
        self._q = queue.Queue()

    def push(self, token):
        self._q.put(int(token))

    def close(self):
        self._q.put(self._CLOSE)

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._CLOSE:
            raise StopIteration
        return item


class _GenRequest:
    __slots__ = ("model", "prompt", "max_tokens", "temperature", "top_k",
                 "seed", "eos_id", "stream", "future", "deadline",
                 "t_submit", "tokens", "token_times", "seq", "priority",
                 "tenant", "trace", "trace_parent")

    def __init__(self, model, prompt, max_tokens, temperature, top_k,
                 seed, eos_id, stream, future, deadline, t_submit, seq,
                 priority="batch", tenant=None):
        self.model = model
        self.prompt = prompt
        self.max_tokens = max_tokens
        self.temperature = temperature
        self.top_k = top_k
        self.seed = int(seed)
        self.eos_id = eos_id
        self.stream = stream
        self.future = future
        self.deadline = deadline
        self.t_submit = t_submit
        self.tokens = []
        self.token_times = []
        self.seq = seq
        self.priority = priority  # admission tier (scheduler.TIERS)
        self.tenant = tenant      # quota/metrics key, or None
        # trace context captured on the submitting thread and
        # re-activated around this request's prefill/decode dispatches
        self.trace = None
        self.trace_parent = None


class _ModelState:
    """Live decode batch of one model: slot table + the KV cache +
    per-slot sampling state (PRNG key chain, temperature, top-k)."""

    def __init__(self, store):
        self.store = store
        self.slots = []                      # _GenRequest or None
        self.lengths = np.zeros(0, np.int32)   # cache frontier per slot
        self.next_tok = np.zeros(0, np.int32)  # next token to consume
        self.temps = np.zeros(0, np.float32)   # <= 0 means greedy
        self.top_ks = np.zeros(0, np.int32)
        self.keys = jnp.zeros((0, 2), jnp.uint32)  # threefry key data
        self.cache_k = None
        self.cache_v = None
        self.C = 0                           # current cache bucket

    def active(self):
        return [i for i, r in enumerate(self.slots) if r is not None]

    def free_slot(self):
        for i, r in enumerate(self.slots):
            if r is None:
                return i
        return None

    def describe(self):
        act = self.active()
        d = {"slots": len(self.slots), "active": len(act),
             "cache_len": self.C,
             "sample_mode": self.store.sample_mode}
        if self.cache_k is not None:
            total = 2 * self.cache_k.size * self.cache_k.dtype.itemsize
            d["cache_mb"] = round(total / 2**20, 3)
            d["cache_dtype"] = str(self.cache_k.dtype)
            # the bf16 claim's measurement: bytes one slot's cache rows
            # occupy at the current bucket depth (halved vs fp32)
            if self.slots:
                d["cache_bytes_per_slot"] = total // len(self.slots)
        return d


class _BlockPool:
    """Host-side allocator over the paged KV pool's physical blocks.

    Block 0 is the reserved trash block (zero table entries point at
    it; non-participating dispatch rows scribble there) and is never
    allocated.  Every allocated block carries a refcount: a sequence
    holding it in its table counts one, each prefix-cache pin counts
    one — a block frees when the last reference drops."""

    def __init__(self, num_blocks):
        self.num_blocks = int(num_blocks)
        self._free = list(range(self.num_blocks - 1, 0, -1))
        self._ref = {}
        self.hwm = 0

    def capacity(self):
        return self.num_blocks - 1

    def used(self):
        return self.capacity() - len(self._free)

    def free_count(self):
        return len(self._free)

    def refcount(self, b):
        return self._ref.get(b, 0)

    def alloc(self):
        """One fresh block at refcount 1, or None when exhausted."""
        if not self._free:
            return None
        b = self._free.pop()
        self._ref[b] = 1
        if self.used() > self.hwm:
            self.hwm = self.used()
        return b

    def ref(self, b):
        self._ref[b] += 1

    def deref(self, b):
        r = self._ref[b] - 1
        if r <= 0:
            del self._ref[b]
            self._free.append(b)
        else:
            self._ref[b] = r
        return r

    def shared(self):
        """Blocks currently referenced more than once."""
        return sum(1 for r in self._ref.values() if r > 1)


class _PrefixStore:
    """Copy-on-write prefix cache: exact prompt prefixes -> pinned
    pool blocks.

    Keys are the token tuples themselves (no hash collisions): a full
    block j of a completed prefill registers under
    ``tuple(prompt[:(j+1)*bs])``; a partial tail block under the WHOLE
    prompt tuple.  Each entry pins one refcount on its block, so
    shared prefixes survive their registering sequence's retirement.
    Matching walks full blocks longest-prefix-first and takes the
    tail only on an exact whole-prompt match — N requests with the
    same system prompt pay its prefill once.  Entries whose pin is
    the LAST reference are evictable (LRU) when the pool runs dry."""

    def __init__(self, pool, block_size):
        self._pool = pool
        self._bs = int(block_size)
        self._entries = collections.OrderedDict()  # tokens -> (blk, n)

    def __len__(self):
        return len(self._entries)

    def match(self, prompt):
        """Longest shared prefix of ``prompt``: ``(full_blocks, tail)``
        — physical block ids for whole shared blocks, plus the tail
        block on an exact whole-prompt match (else None).  Touches the
        matched entries' LRU position; refcounts are NOT taken (the
        caller refs what it actually adopts)."""
        bs = self._bs
        blocks = []
        j = 0
        while (j + 1) * bs <= len(prompt):
            key = tuple(prompt[:(j + 1) * bs])
            e = self._entries.get(key)
            if e is None or e[1] != bs:
                break
            self._entries.move_to_end(key)
            blocks.append(e[0])
            j += 1
        tail = None
        nt = len(prompt) % bs
        if nt and j == len(prompt) // bs:
            e = self._entries.get(tuple(prompt))
            if e is not None and e[1] == nt:
                self._entries.move_to_end(tuple(prompt))
                tail = e[0]
        return blocks, tail

    def register(self, prompt, table_row):
        """Pin a completed prefill's blocks for future sharing (+1
        refcount per NEW entry; prefixes already registered — possibly
        against different physical blocks — are left alone)."""
        bs = self._bs
        for j in range(len(prompt) // bs):
            key = tuple(prompt[:(j + 1) * bs])
            if key in self._entries:
                continue
            b = int(table_row[j])
            self._pool.ref(b)
            self._entries[key] = (b, bs)
        nt = len(prompt) % bs
        if nt:
            key = tuple(prompt)
            if key not in self._entries:
                b = int(table_row[len(prompt) // bs])
                self._pool.ref(b)
                self._entries[key] = (b, nt)

    def evictable(self):
        """Pins whose block would FREE on eviction (refcount 1)."""
        return sum(1 for b, _n in self._entries.values()
                   if self._pool.refcount(b) == 1)

    def evict_one(self):
        """Drop the least-recently-used pin whose block frees (blocks
        still held by live sequences stay).  True when a block was
        reclaimed."""
        for key, (b, _n) in self._entries.items():
            if self._pool.refcount(b) == 1:
                del self._entries[key]
                self._pool.deref(b)
                return True
        return False


class _PagedModelState:
    """Live paged decode batch of one model: slot table + per-slot
    block tables over the global KV pool + the prefix cache.

    Unlike the contiguous :class:`_ModelState`, this PERSISTS across
    batch drains — the prefix cache's pinned blocks are the point of
    keeping it — so ``store.cache_state`` stays attached until the
    engine closes."""

    paged = True

    def __init__(self, store):
        self.store = store
        self.pool = _BlockPool(store.pool_blocks)
        self.prefix = _PrefixStore(self.pool, store.kv_block)
        self.pool_k, self.pool_v = store.new_pool()
        self.tb = store.table_width()
        self.slots = []                        # _GenRequest or None
        self.tables = np.zeros((0, self.tb), np.int32)
        self.lengths = np.zeros(0, np.int32)   # KV frontier per slot
        self.prog = np.zeros(0, np.int32)      # prompt tokens consumed
        self.decoding = np.zeros(0, bool)      # prompt done, generating
        self.chunks_done = np.zeros(0, np.int32)
        self.next_tok = np.zeros(0, np.int32)
        self.temps = np.zeros(0, np.float32)
        self.top_ks = np.zeros(0, np.int32)
        self.resv = np.zeros(0, np.int32)      # reserved-unallocated
        self.keys = jnp.zeros((0, 2), jnp.uint32)
        self.g_used = None                     # pool gauges (engine)
        self.g_hwm = None

    def active(self):
        return [i for i, r in enumerate(self.slots) if r is not None]

    def free_slot(self):
        for i, r in enumerate(self.slots):
            if r is None:
                return i
        return None

    def reserved_total(self):
        return int(self.resv.sum())

    def describe(self):
        act = self.active()
        pool_bytes = 2 * self.pool_k.size * self.pool_k.dtype.itemsize
        per_block = pool_bytes // self.store.pool_blocks
        d = {"slots": len(self.slots), "active": len(act),
             "paged": True,
             "sample_mode": self.store.sample_mode,
             "block_size": self.store.kv_block,
             "prefill_chunk": self.store.prefill_chunk,
             "pool_blocks": self.pool.capacity(),
             "pool_blocks_used": self.pool.used(),
             "pool_blocks_hwm": self.pool.hwm,
             "pool_blocks_shared": self.pool.shared(),
             "pool_blocks_reserved": self.reserved_total(),
             "prefix_entries": len(self.prefix),
             "cache_mb": round(pool_bytes / 2**20, 3),
             "block_bytes": per_block,
             "cache_dtype": str(self.pool_k.dtype)}
        if act:
            # the paged memory claim's measurement: pool bytes
            # actually BACKING the live sequences, per sequence —
            # shared prefix blocks are paid once, so prefix-heavy
            # schedules drive this far under the contiguous plane's
            # cache_bytes_per_slot
            d["cache_bytes_per_active_seq"] = \
                (self.pool.used() * per_block) // len(act)
        return d


class GenerationEngine:
    """Continuous-batching autoregressive generation over a
    :class:`~.registry.ModelRegistry`'s generative models.

    ``submit(model, tokens, ...)`` returns a
    ``concurrent.futures.Future`` resolving to a
    :class:`GenerationResult`.  One engine serves every generative
    model in the registry; prefill batches and decode steps never mix
    models.
    """

    def __init__(self, registry, max_active=None, max_inflight=None,
                 owner_index=None, tenant_quotas=None):
        self._registry = registry
        self._max_active = (int(max_active) if max_active is not None
                            else None)
        if max_inflight is None:
            max_inflight = int(get_env("MXNET_SERVE_MAX_INFLIGHT"))
        self._max_inflight = max(0, int(max_inflight))  # 0 = unbounded
        self._inflight = 0
        # owning replica index (None = bare engine): every ServeClosed
        # minted here carries it — see scheduler.ServeClosed
        self._owner_index = owner_index
        # per-tenant admission quotas: tenant id -> max inflight TOKENS
        # (prompt + max_tokens over the tenant's unresolved requests)
        self._tenant_quotas = dict(tenant_quotas or {})
        self._tenant_tokens = {}
        self._queue = queue.Queue()
        self._waiting = {}     # model -> deque[_GenRequest]
        self._states = {}      # model -> _ModelState
        self._closed = False
        self._seq = 0
        self._submit_lock = make_lock("serving.gen_submit")
        self._stats_lock = make_lock("serving.gen_stats")
        # counters live in the process metrics registry (one labeled
        # series per engine); stats() reads THROUGH them —
        # decode_fetch_elems counts host elements fetched from
        # decode-step outputs (tokens in graph-sampling mode, logits in
        # host mode): per decode_step it is the per-step fetch
        # footprint the in-graph sampler shrinks from (slots, vocab)
        # to (slots,) — pinned by tests
        self._mlabels = {"engine": "gen%d" % _uid()}
        self._stats = _metrics.CounterDict(
            "serve_gen_",
            ("requests", "prefills", "prefill_seqs", "decode_steps",
             "generated_tokens", "finished", "timeouts", "cancelled",
             "errors", "shed", "cache_grows", "slot_grows",
             "decode_fetch_elems",
             # paged-plane counters (zero on contiguous engines):
             # prefix_hits counts admissions that reused shared
             # blocks, *_blocks/_tokens their sizes; cow_forks the
             # copy-on-write block duplications; prefill_chunks the
             # chunk dispatches; shed_pool the requests too large for
             # the pool
             "prefix_hits", "prefix_hit_blocks", "prefix_hit_tokens",
             "cow_forks", "prefill_chunks", "shed_pool"),
            labels=self._mlabels, help="generation engine counter")
        self._g_inflight = _metrics.gauge(
            "serve_gen_inflight", labels=self._mlabels,
            help="accepted-but-unresolved generation requests")
        self._max_active_seen = 0   # high-water mark (stats)
        # high-water cache geometry per model (survives the cache being
        # dropped when a batch drains — the bf16 bytes-per-slot bench
        # evidence reads this instead of racing a live batch)
        self._cache_hwm = {}
        # test seam: (model, seq) admission order; bounded so a
        # long-lived serving process never accumulates it
        self._admit_log = collections.deque(maxlen=4096)
        self._admit_fns = {}   # (prefill shape, cache shape) -> jitted
        self._completer = FutureCompleter("mxt-gen-done")
        self._thread = threading.Thread(target=self._serve_loop,
                                        name="mxt-gen", daemon=True)
        self._thread.start()

    def _closed_exc(self, msg):
        return ServeClosed(msg, replica_index=self._owner_index)

    # -- client side ---------------------------------------------------
    def submit(self, model, tokens, max_tokens=16, temperature=0.0,
               top_k=0, seed=0, eos_id=None, stream=None, timeout=None,
               priority=None, tenant=None):
        """Enqueue one generation request; returns its Future.

        ``tokens`` — prompt token ids (non-empty); ``max_tokens`` —
        generation cap (>= 1; the prompt+generation total must fit
        ``MXNET_SERVE_KV_MAX``); ``temperature <= 0`` is greedy,
        otherwise seeded temperature sampling over the ``top_k``
        highest logits (``top_k=0`` = full vocab) — the token stream is
        a pure function of ``seed`` (a per-request threefry key chain,
        split once per token), identical under in-graph AND host
        sampling and invariant to batch composition; ``eos_id`` stops
        early; ``stream`` — an optional :class:`TokenStream` receiving
        tokens as they are sampled; ``timeout`` (seconds) bounds
        time-to-admission.

        ``priority`` ("latency"/"batch", default "batch") orders the
        waiting deque: latency requests admit before batch requests of
        the same model.  ``tenant`` keys the per-tenant TOKEN quota
        (constructor ``tenant_quotas``: prompt+max_tokens over the
        tenant's unresolved requests) — a tenant over budget is shed
        alone with :class:`ServeOverloaded`."""
        if self._closed:
            # cheap early gate: every post-close submit raises
            # ServeClosed, never a validation error about its payload
            raise self._closed_exc("generation engine is closed")
        priority = "batch" if priority is None else str(priority)
        if priority not in TIERS:
            raise MXNetError("unknown priority tier %r (want one of %s)"
                             % (priority, "/".join(TIERS)))
        tenant = None if tenant is None else str(tenant)
        store = self._registry.gen_store(model)
        # coerce EVERY request field up front, mapping coercion errors
        # to MXNetError (the front door's 400 class — a malformed body
        # is a client error, not a 500) and, crucially, BEFORE the
        # admission bookkeeping: a ValueError after the inflight
        # increment would leak the budget slot forever (no future ever
        # carries the decrement)
        try:
            prompt = [int(t) for t in tokens]
            max_tokens = int(max_tokens)
            temperature = float(temperature)
            top_k = int(top_k)
            seed = int(seed)
            eos_id = None if eos_id is None else int(eos_id)
            timeout = None if timeout is None else float(timeout)
        except (TypeError, ValueError) as e:
            raise MXNetError("invalid generation parameter: %s" % e)
        if not prompt:
            raise MXNetError("empty prompt")
        vocab = store.spec["vocab_size"]
        if min(prompt) < 0 or max(prompt) >= vocab:
            raise MXNetError("prompt token out of range [0, %d)" % vocab)
        if max_tokens < 1:
            raise MXNetError("max_tokens must be >= 1")
        store.validate_request(len(prompt), max_tokens)
        fut = Future()
        now = time.monotonic()
        # trace context: an ingress trace active on this thread (HTTP
        # handler, replica-set placement) rides the request; a bare
        # in-process submit mints its own
        ctx = _tracing.current_context()
        owned = None
        if ctx is None:
            owned = _tracing.start_trace("serve.generate", model=model)
            ctx = (owned, owned.root_id)
        cost = len(prompt) + max_tokens   # the tenant-quota unit
        try:
            with self._submit_lock:
                if self._closed:
                    raise self._closed_exc("generation engine is closed")
                if self._max_inflight \
                        and self._inflight >= self._max_inflight:
                    self._stats.inc("shed")
                    raise ServeOverloaded(
                        "generation engine is at its inflight budget "
                        "(%d); request shed — back off and retry"
                        % self._max_inflight)
                quota = self._tenant_quotas.get(tenant) \
                    if tenant is not None else None
                if quota is not None and \
                        self._tenant_tokens.get(tenant, 0) + cost > quota:
                    # only the noisy tenant sheds; other tenants'
                    # admission is untouched
                    self._stats.inc("shed")
                    _metrics.cached_counter(
                        "serve_tenant_shed_total",
                        labels={"tenant": tenant},
                        help="requests shed by per-tenant quota").inc()
                    raise ServeOverloaded(
                        "tenant %r is over its inflight token quota "
                        "(%d); request shed — back off and retry"
                        % (tenant, quota))
                self._inflight += 1
                if tenant is not None:
                    self._tenant_tokens[tenant] = \
                        self._tenant_tokens.get(tenant, 0) + cost
                self._g_inflight.set(self._inflight)
                req = _GenRequest(
                    model, prompt, max_tokens, temperature,
                    top_k, seed, eos_id, stream, fut,
                    now + timeout if timeout is not None else None,
                    time.perf_counter(), self._seq,
                    priority=priority, tenant=tenant)
                req.trace, req.trace_parent = ctx
                self._seq += 1
                self._queue.put(req)
        except (ServeClosed, ServeOverloaded) as e:
            # export the self-minted trace with the shed/closed status
            # (outside the lock) instead of dropping it unfinished
            if owned is not None:
                owned.finish(status=type(e).__name__)
            raise
        fut.add_done_callback(
            lambda f, t=tenant, c=cost: self._note_resolved(t, c))
        if owned is not None:
            fut.add_done_callback(_tracing.finish_on_done(owned))
        self._stats.inc("requests")
        _metrics.cached_counter(
            "serve_gen_tier_requests_total", labels={"tier": priority},
            help="generation requests accepted, by priority tier").inc()
        if tenant is not None:
            _metrics.cached_counter(
                "serve_gen_tenant_requests_total",
                labels={"tenant": tenant},
                help="generation requests accepted, by tenant").inc()
        return fut

    def _note_resolved(self, tenant, cost):
        with self._submit_lock:
            self._inflight -= 1
            if tenant is not None:
                left = self._tenant_tokens.get(tenant, 0) - cost
                if left > 0:
                    self._tenant_tokens[tenant] = left
                else:
                    self._tenant_tokens.pop(tenant, None)
            self._g_inflight.set(self._inflight)

    def alive(self):
        """Liveness witness (the front door's /healthz reads it)."""
        return not self._closed and self._thread.is_alive()

    def stats(self):
        out = self._stats.as_dict()
        with self._stats_lock:
            out["max_active"] = self._max_active_seen
            out["cache_hwm"] = dict(self._cache_hwm)
        with self._submit_lock:
            out["inflight"] = self._inflight
            out["tenant_tokens"] = dict(self._tenant_tokens)
        out["max_inflight"] = self._max_inflight
        out["tenant_quotas"] = dict(self._tenant_quotas)
        out["models"] = {m: st.describe()
                         for m, st in dict(self._states).items()}
        return out

    def close(self, drain=True, timeout=120.0):
        """Stop the engine.  ``drain=True`` (default) runs every
        admitted AND queued generation to completion first —
        kill-the-server-under-load keeps its promises; ``drain=False``
        fails queued and in-flight work fast with ServeClosed.
        Idempotent; joins the engine thread."""
        with self._submit_lock:
            if not self._closed:
                self._closed = True
                self._drain_on_stop = bool(drain)
                self._queue.put(_STOP)
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise MXNetError("generation engine thread failed to stop "
                             "within %.0fs" % timeout)
        self._completer.close(timeout)
        # retire this engine's labeled series from the process scrape
        _metrics.drop(self._mlabels)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- engine thread -------------------------------------------------
    def _serve_loop(self):
        try:
            stopping = False
            while True:
                stopping = self._pump(stopping) or stopping
                if stopping and not getattr(self, "_drain_on_stop", True):
                    self._fail_all()
                    return
                self._admit_ready()
                self._decode_tick()
                if stopping and not self._has_work():
                    return
        finally:
            # same exit contract as the forward engine: the loop is
            # gone (clean close OR crash), so latch closed and fail
            # anything still queued/waiting/in-flight — an accepted
            # request is never silently dropped.  A crash additionally
            # dumps the flight ring as a postmortem naming the failure.
            exc = sys.exc_info()[1]
            if exc is not None:
                fl = _tracing.flight()
                fl.record("crash", "generation engine loop",
                          error=repr(exc))
                fl.dump(reason="generation engine loop crashed: %r"
                        % (exc,))
            with self._submit_lock:
                self._closed = True
            while True:
                try:
                    item = self._queue.get_nowait()
                except queue.Empty:
                    break
                if item is not _STOP:
                    self._fail_request(item, self._closed_exc(
                        "generation engine dispatch loop exited before "
                        "this request could be served"))
            self._fail_all()

    def _has_work(self):
        if any(self._waiting.values()):
            return True
        return any(st.active() for st in self._states.values())

    def _pump(self, stopping):
        """Move queued requests into the per-model FIFO waiting deques.
        Blocks only when the engine is otherwise idle (close() unblocks
        via the _STOP sentinel).  Returns True when _STOP was seen."""
        stop_seen = False
        block = not stopping and not self._has_work()
        while True:
            try:
                item = self._queue.get() if block \
                    else self._queue.get_nowait()
            except queue.Empty:
                break
            block = False
            if item is _STOP:
                stop_seen = True
                continue
            dq = self._waiting.setdefault(item.model,
                                          collections.deque())
            if item.priority == TIERS[0]:
                # each waiting deque is kept [latency..., batch...]:
                # a latency arrival admits before every parked batch
                # request (after older latency ones — FIFO holds
                # within a tier)
                pos = len(dq)
                for i, parked in enumerate(dq):
                    if parked.priority != TIERS[0]:
                        pos = i
                        break
                dq.insert(pos, item)
            else:
                dq.append(item)
        return stop_seen

    # -- admission (prefill) -------------------------------------------
    def _admit_ready(self):
        for model in list(self._waiting):
            dq = self._waiting.get(model)
            if dq:
                self._admit_model(model, dq)
            if not self._waiting.get(model):
                self._waiting.pop(model, None)

    def _admit_model(self, model, dq):
        try:
            store = self._registry.gen_store(model)
        except MXNetError as e:  # model removed after submit
            while dq:
                self._fail_request(dq.popleft(), e)
            return
        if getattr(store, "paged", False):
            self._admit_paged(model, dq, store)
            return
        st = self._states.get(model)
        cap = store.max_slots()
        if self._max_active is not None:
            cap = min(cap, self._max_active)
        active = len(st.active()) if st else 0
        free = cap - active
        group = []
        now = time.monotonic()
        while dq and len(group) < free:
            r = dq.popleft()
            if r.deadline is not None and now > r.deadline:
                self._fail_request(r, ServeTimeout(
                    "generation request for %r timed out after %.1f ms "
                    "in queue" % (model, (now - r.t_submit) * 1e3)),
                    kind="timeouts")
            elif r.future.set_running_or_notify_cancel():
                group.append(r)
            else:
                self._stats.inc("cancelled")
        if not group:
            return
        toks, lens = store.pad_prompts([r.prompt for r in group])
        try:
            # one prefill serves the whole admitted group: its span
            # lands in every member's trace
            with _tracing.activate_many(
                    [(r.trace, r.trace_parent) for r in group]):
                first_logits, pk, pv = self._dispatch_prefill(
                    store, toks, lens)
            logits = np.asarray(first_logits)
        except BaseException as e:  # noqa: BLE001 — forwarded to futures
            exc = e if isinstance(e, MXNetError) \
                else MXNetError("prefill dispatch failed: %r" % (e,))
            _tracing.flight().record(
                "error", "prefill_dispatch_failed", model=model,
                error=repr(e), requests=len(group))
            for r in group:
                self._fail_request(r, exc, running=True)
            return
        self._stats.inc("prefills")
        self._stats.inc("prefill_seqs", len(group))
        # first generated token (the TTFT moment): one shared-sampler
        # call over the FULL prefill bucket's rows (pad rows sample
        # junk harmlessly — constant shapes mean the jitted sampler
        # compiles once per batch bucket, never inside steady-state
        # admissions) with each request's INITIAL key; the carry keys
        # seed the per-slot chains, so decode steps — in-graph or
        # host — continue the same deterministic stream
        from .program_store import host_sample
        bb = logits.shape[0]
        keys0 = np.zeros((bb, 2), np.uint32)
        temps0 = np.zeros((bb,), np.float32)
        tks0 = np.zeros((bb,), np.int32)
        for i, r in enumerate(group):
            keys0[i] = np.asarray(jax.random.PRNGKey(r.seed))
            temps0[i] = r.temperature
            tks0[i] = r.top_k
        first_toks, carry = host_sample(logits, keys0, temps0, tks0)
        first_toks = np.asarray(first_toks)
        carry = np.asarray(carry)
        survivors = []
        for i, r in enumerate(group):
            self._admit_log.append((model, r.seq))
            tok = int(first_toks[i])
            self._push_token(r, tok)
            if self._finished_reason(r, tok):
                self._finish(r, self._finished_reason(r, tok))
            else:
                survivors.append((i, r))
        if not survivors:
            return
        if st is None:
            st = self._states[model] = _ModelState(store)
            store.cache_state = st
        need = len(st.active()) + len(survivors)
        if need > len(st.slots):
            self._grow_slots(st, store, store.batch_bucket(need))
        Cp = int(pk.shape[3])
        if st.cache_k is None:
            st.cache_k, st.cache_v = store.new_cache(len(st.slots), Cp)
            st.C = Cp
        elif Cp > st.C:
            self._grow_cache(st, store.kv_bucket(Cp))
        # np.array COPIES: asarray of a jax array is a read-only view
        slot_keys = np.array(st.keys, np.uint32)
        for i, r in survivors:
            slot = st.free_slot()
            self._admit_row(st, pk, pv, i, slot)
            st.slots[slot] = r
            st.lengths[slot] = len(r.prompt)
            st.next_tok[slot] = r.tokens[-1]
            st.temps[slot] = r.temperature
            st.top_ks[slot] = r.top_k
            slot_keys[slot] = carry[i]
        st.keys = jnp.asarray(slot_keys)
        self._note_cache_hwm(model, st)
        with self._stats_lock:
            if len(st.active()) > self._max_active_seen:
                self._max_active_seen = len(st.active())

    def _note_cache_hwm(self, model, st):
        d = st.describe()
        with self._stats_lock:
            prev = self._cache_hwm.get(model)
            if prev is None or d.get("cache_mb", 0.0) >= \
                    prev.get("cache_mb", 0.0):
                self._cache_hwm[model] = d

    def _admit_row(self, st, pk, pv, row, slot):
        """Copy one prefilled sequence's cache rows into a decode slot
        (device-side; the batch cache is consumed and rebound)."""
        key = (tuple(pk.shape), tuple(st.cache_k.shape))
        fn = self._admit_fns.get(key)
        if fn is None:
            Cp, C = int(pk.shape[3]), int(st.cache_k.shape[3])

            def f(ck, cv, pk_, pv_, slot_, row_):
                rk = jax.lax.dynamic_slice_in_dim(pk_, row_, 1, 1)
                rv = jax.lax.dynamic_slice_in_dim(pv_, row_, 1, 1)
                pad = ((0, 0), (0, 0), (0, 0), (0, C - Cp), (0, 0))
                rk = jnp.pad(rk, pad)
                rv = jnp.pad(rv, pad)
                ck = jax.lax.dynamic_update_slice(
                    ck, rk, (0, slot_, 0, 0, 0))
                cv = jax.lax.dynamic_update_slice(
                    cv, rv, (0, slot_, 0, 0, 0))
                return ck, cv

            from .program_store import cache_donate_argnums
            fn = jax.jit(f, donate_argnums=cache_donate_argnums((0, 1)))
            self._admit_fns[key] = fn
        st.cache_k, st.cache_v = fn(st.cache_k, st.cache_v, pk, pv,
                                    np.int32(slot), np.int32(row))

    def _grow_slots(self, st, store, new_bb):
        grow = new_bb - len(st.slots)
        st.slots.extend([None] * grow)
        st.lengths = np.concatenate(
            [st.lengths, np.zeros(grow, np.int32)])
        st.next_tok = np.concatenate(
            [st.next_tok, np.zeros(grow, np.int32)])
        st.temps = np.concatenate(
            [st.temps, np.zeros(grow, np.float32)])
        st.top_ks = np.concatenate(
            [st.top_ks, np.zeros(grow, np.int32)])
        st.keys = jnp.concatenate(
            [st.keys, jnp.zeros((grow, 2), jnp.uint32)])
        if st.cache_k is not None:
            pad = ((0, 0), (0, grow), (0, 0), (0, 0), (0, 0))
            st.cache_k = jnp.pad(st.cache_k, pad)
            st.cache_v = jnp.pad(st.cache_v, pad)
        self._stats.inc("slot_grows")

    def _grow_cache(self, st, new_c):
        pad = ((0, 0), (0, 0), (0, 0), (0, new_c - st.C), (0, 0))
        st.cache_k = jnp.pad(st.cache_k, pad)
        st.cache_v = jnp.pad(st.cache_v, pad)
        st.C = new_c
        self._stats.inc("cache_grows")
        self._note_cache_hwm(st.store.name, st)

    # -- paged plane ---------------------------------------------------
    def _paged_state(self, model, store):
        st = self._states.get(model)
        if st is None:
            st = self._states[model] = _PagedModelState(store)
            store.cache_state = st
            lbl = dict(self._mlabels, model=model)
            st.g_used = _metrics.gauge(
                "serve_kv_pool_blocks_used", labels=lbl,
                help="paged KV pool blocks currently allocated")
            st.g_hwm = _metrics.gauge(
                "serve_kv_pool_blocks_hwm", labels=lbl,
                help="paged KV pool allocation high-water mark")
        return st

    def _paged_gauges(self, st):
        st.g_used.set(st.pool.used())
        st.g_hwm.set(st.pool.hwm)

    def _paged_alloc(self, st):
        """One fresh pool block, evicting LRU prefix pins if the free
        list is dry.  Exhaustion raises — admission reservations exist
        to make that unreachable."""
        b = st.pool.alloc()
        while b is None and st.prefix.evict_one():
            b = st.pool.alloc()
        if b is None:
            raise MXNetError(
                "paged KV pool exhausted (%d blocks) — admission "
                "reservations should have prevented this"
                % st.pool.capacity())
        return b

    def _admit_paged(self, model, dq, store):
        """Paged admission: no prefill dispatch here — a slot is
        claimed, its block table seeded from the prefix cache (shared
        blocks adopted at +1 refcount each), and the prompt's
        remaining tokens left for the tick loop to chunk through.
        FIFO, never overtaking: the head request waiting on pool
        space blocks everyone behind it."""
        st = self._paged_state(model, store)
        bs = store.kv_block
        cap = store.max_slots()
        if self._max_active is not None:
            cap = min(cap, self._max_active)
        admitted = 0
        while dq:
            now = time.monotonic()
            r = dq[0]
            if r.deadline is not None and now > r.deadline:
                dq.popleft()
                self._fail_request(r, ServeTimeout(
                    "generation request for %r timed out after %.1f ms "
                    "in queue" % (model, (now - r.t_submit) * 1e3)),
                    kind="timeouts")
                continue
            if len(st.active()) >= cap:
                break
            total_blocks = -(-(len(r.prompt) + r.max_tokens) // bs)
            blocks, tail = st.prefix.match(r.prompt)
            # a partially-filled last prompt block gets pinned by the
            # prefix cache at registration, so the first decode write
            # into it MUST copy-on-write-fork — one allocation past
            # total_blocks.  A tail HIT already counts its fork target
            # in total_blocks (the borrowed block is free).
            fork_extra = int(len(r.prompt) % bs != 0 and tail is None)
            needed = total_blocks - len(blocks) + fork_extra
            if total_blocks + fork_extra > st.pool.capacity():
                # can never fit, even against an empty pool: shed
                dq.popleft()
                self._stats.inc("shed_pool")
                self._stats.inc("shed")
                self._fail_request(r, ServeOverloaded(
                    "request needs %d KV blocks, past the paged "
                    "pool's %d usable blocks — shed"
                    % (total_blocks + fork_extra, st.pool.capacity())))
                continue
            budget = (st.pool.free_count() + st.prefix.evictable() -
                      st.reserved_total())
            if needed > budget:
                break   # wait for retirements; no overtaking
            dq.popleft()
            if not r.future.set_running_or_notify_cancel():
                self._stats.inc("cancelled")
                continue
            slot = st.free_slot()
            if slot is None:
                need = len(st.active()) + 1
                self._grow_paged_slots(st, store,
                                       store.batch_bucket(need))
                slot = st.free_slot()
            row = st.tables[slot]
            row[:] = 0
            for j, b in enumerate(blocks):
                row[j] = b
                st.pool.ref(b)
            covered = len(blocks) * bs
            if tail is not None:
                row[len(blocks)] = tail
                st.pool.ref(tail)
                covered = len(r.prompt)
            if covered:
                self._stats.inc("prefix_hits")
                self._stats.inc("prefix_hit_blocks",
                                len(blocks) + (tail is not None))
                self._stats.inc("prefix_hit_tokens", covered)
                _metrics.cached_counter(
                    "serve_prefix_hit_total",
                    help="admissions that reused shared paged-KV "
                         "prefix blocks").inc()
            # shared tokens skip recomputation, but the LAST prompt
            # token always reruns: its logits seed the first sample
            prog = min(covered, len(r.prompt) - 1)
            st.prog[slot] = prog
            st.lengths[slot] = prog
            st.decoding[slot] = False
            st.chunks_done[slot] = 0
            st.slots[slot] = r
            st.next_tok[slot] = 0
            st.temps[slot] = r.temperature
            st.top_ks[slot] = r.top_k
            st.resv[slot] = needed
            keys = np.array(st.keys, np.uint32)
            keys[slot] = np.asarray(jax.random.PRNGKey(r.seed))
            st.keys = jnp.asarray(keys)
            self._admit_log.append((model, r.seq))
            admitted += 1
        if admitted:
            self._stats.inc("prefill_seqs", admitted)
            self._note_cache_hwm(model, st)
            with self._stats_lock:
                if len(st.active()) > self._max_active_seen:
                    self._max_active_seen = len(st.active())
        self._paged_gauges(st)

    def _grow_paged_slots(self, st, store, new_bb):
        grow = new_bb - len(st.slots)
        st.slots.extend([None] * grow)
        st.tables = np.concatenate(
            [st.tables, np.zeros((grow, st.tb), np.int32)])
        for name in ("lengths", "prog", "chunks_done", "next_tok",
                     "top_ks", "resv"):
            arr = getattr(st, name)
            setattr(st, name, np.concatenate(
                [arr, np.zeros(grow, arr.dtype)]))
        st.decoding = np.concatenate(
            [st.decoding, np.zeros(grow, bool)])
        st.temps = np.concatenate(
            [st.temps, np.zeros(grow, np.float32)])
        st.keys = jnp.concatenate(
            [st.keys, jnp.zeros((grow, 2), jnp.uint32)])
        self._stats.inc("slot_grows")

    def _release_paged_slot(self, st, i):
        """Drop slot i's block references and bookkeeping (retire and
        failure paths; the prefix cache's pins keep shared blocks
        alive past this)."""
        for j in range(st.tb):
            b = int(st.tables[i, j])
            if b:
                st.pool.deref(b)
        st.tables[i, :] = 0
        st.slots[i] = None
        st.lengths[i] = 0
        st.prog[i] = 0
        st.decoding[i] = False
        st.chunks_done[i] = 0
        st.next_tok[i] = 0
        st.temps[i] = 0.0
        st.top_ks[i] = 0
        st.resv[i] = 0

    def _paged_tick(self, model, st):
        """One engine tick of the paged plane: ONE decode step for the
        generating slots, then ONE prompt chunk for the prefilling
        slots — long prompts advance prefill_chunk tokens per tick
        INTERLEAVED with everyone else's decode steps, so a long
        prefill stops spiking co-running streams' inter-token
        latency."""
        dec = [i for i in st.active() if st.decoding[i]]
        if dec:
            self._paged_decode_step(model, st, dec)
        pre = [i for i in st.active() if not st.decoding[i]]
        if pre:
            self._paged_prefill_chunk(model, st, pre)
        if dec or pre:
            self._paged_gauges(st)

    def _paged_write_ready(self, st, i, positions):
        """Make slot i's table writable at ``positions``: allocate
        entries still at 0 and copy-on-write-fork any covering block
        someone else also references (refcount > 1 — a shared prefix
        tail, or a block pinned by the prefix cache).  Generation
        writes past the registered prompt MUST fork; recomputed prompt
        positions rewrite shared blocks with bit-identical values, so
        they are exempted by callers passing only new positions."""
        bs = st.store.kv_block
        for j in sorted({p // bs for p in positions}):
            b = int(st.tables[i, j])
            if b == 0:
                st.tables[i, j] = self._paged_alloc(st)
                st.resv[i] = max(0, int(st.resv[i]) - 1)
            elif st.pool.refcount(b) > 1:
                nb = self._paged_alloc(st)
                st.pool_k, st.pool_v = st.store.copy_block(
                    st.pool_k, st.pool_v, b, nb)
                st.pool.deref(b)
                st.tables[i, j] = nb
                st.resv[i] = max(0, int(st.resv[i]) - 1)
                self._stats.inc("cow_forks")

    def _paged_dispatch(self, st, tables, toks, pos, val, do, phase):
        """One unified paged step (decode OR prompt chunk — ``phase``
        names it for the profiler/traces) + one sampled token per
        ``do`` row, host-side np result.  Same graph/host sampling
        split as the contiguous plane's ``_decode_and_sample``."""
        if st.store.sample_mode == "graph":
            t0 = time.perf_counter_ns()
            toks_dev, st.pool_k, st.pool_v, st.keys = \
                st.store.run_paged_step_sample(
                    st.pool_k, st.pool_v, tables, toks, pos, val,
                    st.keys, st.temps, st.top_ks, do)
            _profiler.record_phase(phase, t0)
            t0 = time.perf_counter_ns()
            sampled = self._fetch_decode(toks_dev)
            _profiler.record_phase("serve_sample", t0)
            return sampled
        t0 = time.perf_counter_ns()
        logits_dev, st.pool_k, st.pool_v = st.store.run_paged_step(
            st.pool_k, st.pool_v, tables, toks, pos, val)
        _profiler.record_phase(phase, t0)
        t0 = time.perf_counter_ns()
        logits = self._fetch_decode(logits_dev)
        from .program_store import host_sample
        toks_out, carry = host_sample(logits, st.keys, st.temps,
                                      st.top_ks)
        st.keys = jnp.where(jnp.asarray(do)[:, None], carry, st.keys)
        sampled = np.asarray(toks_out)
        _profiler.record_phase("serve_sample", t0)
        return sampled

    def _paged_decode_step(self, model, st, dec):
        """Advance every generating slot one token (serve_decode
        phase).  Slots mid-prefill (and empty slots) ride the dispatch
        with all-zero tables — their writes land in the trash block
        and their outputs are discarded."""
        for i in dec:
            # the write position this step: COW-fork or allocate first
            self._paged_write_ready(st, i, [int(st.lengths[i])])
        n = len(st.slots)
        tables = np.zeros((n, st.tb), np.int32)
        toks = np.zeros((n, 1), np.int32)
        pos = np.zeros((n,), np.int32)
        val = np.ones((n,), np.int32)
        do = np.zeros((n,), bool)
        for i in dec:
            tables[i] = st.tables[i]
            toks[i, 0] = st.next_tok[i]
            pos[i] = st.lengths[i]
            do[i] = True
        try:
            with _tracing.activate_many(
                    [(st.slots[i].trace, st.slots[i].trace_parent)
                     for i in dec]):
                sampled = self._paged_dispatch(st, tables, toks, pos,
                                               val, do, "serve_decode")
        except BaseException as e:  # noqa: BLE001 — to the futures
            exc = e if isinstance(e, MXNetError) \
                else MXNetError("decode dispatch failed: %r" % (e,))
            _tracing.flight().record(
                "error", "decode_dispatch_failed", model=model,
                error=repr(e), slots=len(dec))
            for i in dec:
                r = st.slots[i]
                self._release_paged_slot(st, i)
                self._fail_request(r, exc, running=True)
            return
        for i in dec:
            r = st.slots[i]
            st.lengths[i] += 1
            tok = int(sampled[i])
            self._push_token(r, tok)
            st.next_tok[i] = tok
            reason = self._finished_reason(r, tok)
            if reason:
                self._release_paged_slot(st, i)
                self._finish(r, reason)
        self._stats.inc("decode_steps")
        self._stats.inc("generated_tokens", len(dec))

    def _paged_prefill_chunk(self, model, st, pre):
        """Advance every prefilling slot one prompt chunk
        (serve_prefill phase).  Rows finishing their prompt this
        dispatch sample their first token (the TTFT moment), register
        their blocks with the prefix cache and flip to decoding."""
        store = st.store
        bs = store.kv_block
        chunk = store.prefill_chunk
        rows = []
        for i in pre:
            r = st.slots[i]
            p0 = int(st.prog[i])
            ntok = min(chunk, len(r.prompt) - p0)
            # blocks covering NEW positions only: recomputed shared
            # positions rewrite shared blocks with identical values
            # (same tokens, same prefix) and must not fork
            fresh = [p for p in range(p0, p0 + ntok)
                     if st.tables[i, p // bs] == 0]
            self._paged_write_ready(st, i, fresh)
            rows.append((i, r, p0, ntok))
        n = len(st.slots)
        tables = np.zeros((n, st.tb), np.int32)
        toks = np.zeros((n, chunk), np.int32)
        pos = np.zeros((n,), np.int32)
        val = np.ones((n,), np.int32)
        do = np.zeros((n,), bool)
        for i, r, p0, ntok in rows:
            tables[i] = st.tables[i]
            toks[i, :ntok] = r.prompt[p0:p0 + ntok]
            pos[i] = p0
            val[i] = ntok
            do[i] = (p0 + ntok == len(r.prompt))
        try:
            with _tracing.activate_many(
                    [(r.trace, r.trace_parent)
                     for _i, r, _p, _n in rows]):
                sampled = self._paged_dispatch(
                    st, tables, toks, pos, val, do, "serve_prefill")
        except BaseException as e:  # noqa: BLE001 — to the futures
            exc = e if isinstance(e, MXNetError) \
                else MXNetError("prefill dispatch failed: %r" % (e,))
            _tracing.flight().record(
                "error", "prefill_dispatch_failed", model=model,
                error=repr(e), requests=len(rows))
            for i, r, _p0, _ntok in rows:
                self._release_paged_slot(st, i)
                self._fail_request(r, exc, running=True)
            return
        self._stats.inc("prefills")
        self._stats.inc("prefill_chunks", len(rows))
        for i, r, p0, ntok in rows:
            st.prog[i] = p0 + ntok
            st.lengths[i] = p0 + ntok
            st.chunks_done[i] += 1
            if p0 + ntok < len(r.prompt):
                continue
            if _metrics.phase_on():
                _H_CHUNKS.observe(int(st.chunks_done[i]))
            st.prefix.register(r.prompt, st.tables[i])
            tok = int(sampled[i])
            self._push_token(r, tok)
            reason = self._finished_reason(r, tok)
            if reason:
                self._release_paged_slot(st, i)
                self._finish(r, reason)
            else:
                st.decoding[i] = True
                st.next_tok[i] = tok
        self._note_cache_hwm(model, st)

    # -- decode --------------------------------------------------------
    def _decode_tick(self):
        for model, st in list(self._states.items()):
            if getattr(st, "paged", False):
                self._paged_tick(model, st)
                continue
            act = st.active()
            if not act:
                # batch drained: drop the cache (and its memory) until
                # the next admission starts fresh
                self._states.pop(model)
                st.store.cache_state = None
                continue
            needed = int(st.lengths[act].max()) + 1
            if needed > st.C:
                self._grow_cache(st, st.store.kv_bucket(needed))
            toks = np.ascontiguousarray(st.next_tok)
            lens = np.ascontiguousarray(st.lengths)
            try:
                # one decode step advances every active slot: its
                # serve_decode/serve_sample spans land in each slot's
                # trace
                with _tracing.activate_many(
                        [(st.slots[i].trace, st.slots[i].trace_parent)
                         for i in act]):
                    sampled = self._decode_and_sample(st, toks, lens)
            except BaseException as e:  # noqa: BLE001 — to the futures
                exc = e if isinstance(e, MXNetError) \
                    else MXNetError("decode dispatch failed: %r" % (e,))
                _tracing.flight().record(
                    "error", "decode_dispatch_failed", model=model,
                    error=repr(e), slots=len(act))
                for i in act:
                    r = st.slots[i]
                    st.slots[i] = None
                    self._fail_request(r, exc, running=True)
                continue
            for i in act:
                r = st.slots[i]
                st.lengths[i] += 1
                tok = int(sampled[i])
                self._push_token(r, tok)
                st.next_tok[i] = tok
                reason = self._finished_reason(r, tok)
                if reason:
                    st.slots[i] = None
                    st.lengths[i] = 0
                    st.next_tok[i] = 0
                    st.temps[i] = 0.0
                    st.top_ks[i] = 0
                    self._finish(r, reason)
            self._stats.inc("decode_steps")
            self._stats.inc("generated_tokens", len(act))

    def _decode_and_sample(self, st, toks, lens):
        """One decode step + one token per slot, host-side np result.

        ``graph`` mode dispatches the sampling decode program (tokens
        out; the per-slot PRNG keys are donated alongside the caches
        and rebound) and fetches ONLY the ``(slots,)`` token vector;
        ``host`` mode dispatches the logits program, fetches the whole
        ``(slots, vocab)`` matrix and runs the SAME jitted sampler on
        it.  Either way the fetch + sampling is bracketed by the
        ``serve_sample`` phase and counted in ``decode_fetch_elems``."""
        if st.store.sample_mode == "graph":
            toks_dev = self._dispatch_decode_sample(st, toks, lens)
            t0 = time.perf_counter_ns()
            sampled = self._fetch_decode(toks_dev)
            _profiler.record_phase("serve_sample", t0)
            return sampled
        logits_dev = self._dispatch_decode(st, toks, lens)
        t0 = time.perf_counter_ns()
        logits = self._fetch_decode(logits_dev)
        from .program_store import host_sample
        toks_out, st.keys = host_sample(logits, st.keys, st.temps,
                                        st.top_ks)
        sampled = np.asarray(toks_out)
        _profiler.record_phase("serve_sample", t0)
        return sampled

    def _fetch_decode(self, arr):
        """THE host fetch of the decode loop — one np conversion whose
        element count feeds ``decode_fetch_elems`` (the zero-logits-
        fetch acceptance pin reads it; tests also spy the shapes
        here)."""
        a = np.asarray(arr)
        self._stats.inc("decode_fetch_elems", int(a.size))
        return a

    @hot_path
    def _dispatch_prefill(self, store, tokens, lengths):
        """Enqueue-only prompt-batch dispatch (serve_prefill phase);
        the logits fetch happens on the caller side."""
        t0 = time.perf_counter_ns()
        out = store.run_prefill(tokens, lengths)
        _profiler.record_phase("serve_prefill", t0)
        return out

    @hot_path
    def _dispatch_decode(self, st, tokens, lengths):
        """Enqueue-only logits-out decode dispatch (serve_decode phase;
        the MXNET_SERVE_SAMPLE=host hatch).  The donated caches are
        rebound to the program's outputs before anything can read the
        consumed buffers."""
        t0 = time.perf_counter_ns()
        logits, st.cache_k, st.cache_v = st.store.run_decode(
            st.cache_k, st.cache_v, tokens, lengths)
        _profiler.record_phase("serve_decode", t0)
        return logits

    @hot_path
    def _dispatch_decode_sample(self, st, tokens, lengths):
        """Enqueue-only sampling decode dispatch (serve_decode phase):
        tokens come out sampled in-graph; the donated caches AND the
        per-slot PRNG key state are rebound to the program's outputs."""
        t0 = time.perf_counter_ns()
        toks, st.cache_k, st.cache_v, st.keys = \
            st.store.run_decode_sample(st.cache_k, st.cache_v, tokens,
                                       lengths, st.keys, st.temps,
                                       st.top_ks)
        _profiler.record_phase("serve_decode", t0)
        return toks

    # -- retirement ----------------------------------------------------
    @staticmethod
    def _finished_reason(req, tok):
        if req.eos_id is not None and tok == req.eos_id:
            return "eos"
        if len(req.tokens) >= req.max_tokens:
            return "length"
        return None

    def _push_token(self, req, tok):
        now = time.perf_counter()
        if _metrics.phase_on():
            if not req.token_times:
                _H_TTFT.observe(now - req.t_submit)
            else:
                _H_ITL.observe(now - req.token_times[-1])
        req.tokens.append(tok)
        req.token_times.append(now)
        if req.stream is not None:
            req.stream.push(tok)

    def _finish(self, req, reason):
        if req.stream is not None:
            req.stream.close()
        res = GenerationResult(req.model, len(req.prompt),
                               list(req.tokens), reason, req.t_submit,
                               list(req.token_times))
        self._completer.resolve(req.future, res)
        self._stats.inc("finished")

    def _fail_request(self, req, exc, kind="errors", running=False):
        if not running and not req.future.set_running_or_notify_cancel():
            self._stats.inc("cancelled")
            return
        if req.stream is not None:
            req.stream.close()
        self._completer.resolve(req.future, exc=exc)
        self._stats.inc(kind)

    def _fail_all(self):
        """close(drain=False): everything waiting or in flight fails
        fast — with the owning replica named, so the retry layer and
        the flight recorder see WHICH replica's kill lost the KV
        state."""
        exc = self._closed_exc(
            "generation engine closed before completion")
        for dq in self._waiting.values():
            while dq:
                self._fail_request(dq.popleft(), exc)
        self._waiting.clear()
        for model, st in list(self._states.items()):
            for i in st.active():
                r = st.slots[i]
                st.slots[i] = None
                self._fail_request(r, exc, running=True)
            st.store.cache_state = None
        self._states.clear()

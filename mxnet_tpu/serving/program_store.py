"""AOT program store: compiled-ahead-of-time inference per shape bucket.

The training side compiles lazily (``cached_op.py``'s tiered LRU,
``executor.py``'s bind-time jit) because training shapes are stable after
step one.  A serving process is the opposite regime: request sizes vary
per call and the first request of a new shape must NOT pay a multi-second
XLA compile.  So the store

* quantizes request batch sizes into configured **bucket edges**
  (``MXNET_SERVE_BUCKETS``): a request of ``n`` rows is zero-padded up to
  the smallest edge ``>= n``, runs the bucket's program, and the pad rows
  are sliced back off every batch-major output.  Inference graphs are
  row-independent (``is_train=False`` — BatchNorm reads running stats,
  softmax is per-row), so the pad rows cannot perturb the real rows and
  fp32 bucketed outputs are **bit-equal** to an unbatched forward
  (pinned by ``tests/test_serving.py``);
* compiles each bucket's program **ahead of time** —
  ``jax.jit(fwd).lower(specs...).compile()`` — normally at model load
  (:meth:`ProgramStore.warmup`), so steady-state dispatch never traces;
* holds the executables in a bounded LRU keyed like ``cached_op.py``'s
  (``(model, bucket, input avals, dtype)``), ``MXNET_SERVE_PROGRAM_CACHE``
  entries, with hit/compile/eviction stats.

Parameters are **arguments** of the compiled programs (not baked
constants like ``deploy.py``'s export), so all buckets share one
device-resident copy of the weights and a model upgrade swaps arrays
without recompiling.  ``compute_dtype='bfloat16'`` casts the floating
weights once at load (half the serving memory) and casts inputs inside
the program; outputs always come back float32.
"""
from __future__ import annotations

import logging
import time
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.lockcheck import make_lock
from ..base import MXNetError, get_env, hot_path
from ..pallas_ops import dispatch as _pallas_dispatch

__all__ = ["ProgramStore", "bucket_edges", "bucket_for"]

log = logging.getLogger(__name__)


def bucket_edges(edges=None):
    """Resolve bucket edges: an explicit iterable, or the
    ``MXNET_SERVE_BUCKETS`` comma list; returned sorted, deduplicated,
    all positive."""
    if edges is None:
        raw = get_env("MXNET_SERVE_BUCKETS")
        edges = [int(tok) for tok in str(raw).split(",") if tok.strip()]
    out = sorted({int(e) for e in edges})
    if not out or out[0] < 1:
        raise MXNetError("serving bucket edges must be positive ints, "
                         "got %r" % (edges,))
    return tuple(out)


def bucket_for(n, edges):
    """Smallest edge >= n, or None when n exceeds the largest edge."""
    for e in edges:
        if n <= e:
            return e
    return None


def _as_device_array(v):
    """Model parameter -> jax array WITHOUT a host round-trip when the
    value is already device-resident (NDArray / jax.Array)."""
    data = getattr(v, "_data", v)  # NDArray unwraps; numpy/jax pass through
    return data if isinstance(data, jax.Array) else jnp.asarray(data)


class _Program:
    __slots__ = ("fn", "bucket", "out_batch_major", "compile_ms")

    def __init__(self, fn, bucket, out_batch_major, compile_ms):
        self.fn = fn
        self.bucket = bucket
        self.out_batch_major = out_batch_major
        self.compile_ms = compile_ms


class ProgramStore:
    """Bucketed AOT-compiled inference programs for one model.

    Parameters
    ----------
    symbol : Symbol
        The inference graph.
    arg_params, aux_params : dict
        name -> array (NDArray / jax / numpy).  Non-input arguments
        missing from ``arg_params`` whose shape is inferable are baked
        as zeros (unused loss-head labels, same policy as ``deploy.py``).
    input_shapes : dict
        name -> full shape; axis 0 of every input is the batch axis the
        store buckets on (the leading dim given here is only a shape
        template — requests of any bucketable size are accepted).
    name : str
        Cache-key / diagnostics tag.
    compute_dtype : str, optional
        ``'bfloat16'`` casts floating weights once at load and inputs
        inside the program; outputs return float32.  None = master
        dtype (fp32 bit-equal serving).
    buckets : iterable of int, optional
        Bucket edges; overrides ``MXNET_SERVE_BUCKETS``.
    max_programs : int, optional
        LRU bound; overrides ``MXNET_SERVE_PROGRAM_CACHE``.
    input_dtypes : dict, optional
        name -> numpy dtype of the wire inputs (default float32).
    device : jax.Device, optional
        Pin weights (and hence the compiled programs, which follow
        their committed arguments) to this device; default leaves
        placement to jax's default device.
    """

    def __init__(self, symbol, arg_params, aux_params, input_shapes,
                 name="model", compute_dtype=None, buckets=None,
                 max_programs=None, input_dtypes=None, device=None):
        self._symbol = symbol
        self.name = name
        self._edges = bucket_edges(buckets)
        self._cdt = jnp.dtype(compute_dtype) if compute_dtype else None
        self._input_names = list(input_shapes)
        if not self._input_names:
            raise MXNetError("serving needs at least one input")
        self._input_tails = {n: tuple(input_shapes[n])[1:]
                             for n in self._input_names}
        self._input_dtypes = {
            n: np.dtype((input_dtypes or {}).get(n, "float32"))
            for n in self._input_names}
        self._device = device
        # bucketing correctness requires every output to carry a leading
        # batch axis: pad rows are sliced off outputs, and the batcher
        # hands each request its row range — an output computed over the
        # WHOLE batch (a mean/sum head) would mix pad rows and, under
        # continuous batching, other requests' rows into every result.
        # Probe the symbol at two distinct batch sizes: batch-major
        # outputs track the batch, anything else is rejected at load.
        out_names = symbol.list_outputs()
        probes = []
        for b in (self._edges[-1], self._edges[-1] + 1):
            probe = {n: (b,) + self._input_tails[n]
                     for n in self._input_names}
            _, out_shapes, _ = symbol.infer_shape_partial(**probe)
            probes.append(out_shapes)
        for i, oname in enumerate(out_names):
            s1, s2 = probes[0][i], probes[1][i]
            if s1 is None or s2 is None or not len(s1) or not len(s2) \
                    or s1[0] != self._edges[-1] \
                    or s2[0] != self._edges[-1] + 1:
                raise MXNetError(
                    "output %r of serving model %r is not batch-major "
                    "(shape %s at batch size %d): bucket padding and "
                    "continuous batching require row-independent "
                    "batch-major outputs — serve this model with the "
                    "classic Predictor instead"
                    % (oname, name, s1, self._edges[-1]))

        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        aux_params = aux_params or {}
        self._param_names = [n for n in arg_names
                             if n not in input_shapes and n in arg_params]
        self._zero_args = [n for n in arg_names
                           if n not in input_shapes
                           and n not in arg_params]

        def load(v):
            a = _as_device_array(v)
            if self._cdt is not None and a.dtype != self._cdt and \
                    jnp.issubdtype(a.dtype, jnp.floating):
                a = a.astype(self._cdt)
            if device is not None:
                # committed params pin the compiled programs' placement
                # (uncommitted request inputs follow them)
                a = jax.device_put(a, device)
            return a

        self._params = {n: load(arg_params[n]) for n in self._param_names}
        aux = []
        # aux states missing from the checkpoint keep predictor.py's
        # policy: zero-filled at their inferred shape
        shapes = {n: tuple(input_shapes[n]) for n in self._input_names}
        _, _, aux_shapes = symbol.infer_shape_partial(**shapes)
        for n, shape in zip(aux_names, aux_shapes):
            if n in aux_params:
                aux.append(load(aux_params[n]))
            elif shape is not None:
                z = jnp.zeros(tuple(shape), self._cdt or jnp.float32)
                aux.append(z if device is None
                           else jax.device_put(z, device))
            else:
                raise MXNetError("auxiliary state %r is not in the params "
                                 "and its shape cannot be inferred" % n)
        self._aux = tuple(aux)

        if max_programs is None:
            max_programs = int(get_env("MXNET_SERVE_PROGRAM_CACHE"))
        self.max_programs = max(1, int(max_programs))
        if self.max_programs < len(self._edges):
            # warmup can't keep every bucket resident: the LRU evicts
            # early buckets before traffic, and the first request for
            # one pays a compile AT DISPATCH — the stall AOT exists to
            # prevent.  Legal (eviction tests rely on it) but worth a
            # loud heads-up in a serving process.
            log.warning(
                "serving model %r: program cache (%d) is smaller than "
                "the bucket count (%d); warmed buckets will be evicted "
                "and recompile inside served requests — raise "
                "MXNET_SERVE_PROGRAM_CACHE or trim MXNET_SERVE_BUCKETS",
                name, self.max_programs, len(self._edges))
        self._programs = OrderedDict()   # key -> _Program
        self._lock = make_lock("serving.program_store")
        self._stats = {"hits": 0, "compiles": 0, "evictions": 0,
                       "compile_ms_total": 0.0}

    # -- geometry ------------------------------------------------------
    @property
    def edges(self):
        return self._edges

    def max_bucket(self):
        return self._edges[-1]

    @property
    def input_names(self):
        return list(self._input_names)

    def output_names(self):
        return self._symbol.list_outputs()

    def canon_inputs(self, inputs):
        """Validate + canonicalize one request's inputs (client-thread
        work: np conversion, dtype cast, shape checks).  Returns
        ``(dict name -> np.ndarray, n_rows)``."""
        got, want = set(inputs), set(self._input_names)
        if got != want:
            raise MXNetError("serving inputs mismatch for %r: got %s, "
                             "want %s" % (self.name, sorted(got),
                                          sorted(want)))
        out = {}
        n = None
        for name in self._input_names:
            a = np.asarray(inputs[name], dtype=self._input_dtypes[name])
            tail = self._input_tails[name]
            if a.ndim != len(tail) + 1 or tuple(a.shape[1:]) != tail:
                raise MXNetError(
                    "input %r has shape %s; want (n,%s)"
                    % (name, a.shape, ",".join(map(str, tail))))
            if n is None:
                n = int(a.shape[0])
            elif int(a.shape[0]) != n:
                raise MXNetError("inputs disagree on batch rows: %d vs %d"
                                 % (n, a.shape[0]))
            out[name] = a
        if n < 1:
            raise MXNetError("empty request (0 rows)")
        if bucket_for(n, self._edges) is None:
            raise MXNetError(
                "request of %d rows exceeds the largest serving bucket "
                "(%d); raise MXNET_SERVE_BUCKETS or split the request"
                % (n, self._edges[-1]))
        return out, n

    # -- compilation ---------------------------------------------------
    def _key(self, bucket):
        sig = tuple((n, (bucket,) + self._input_tails[n],
                     str(self._input_dtypes[n]))
                    for n in self._input_names)
        # the Pallas dispatch fingerprint rides in the key like in the
        # cached-op and SPMD program caches: bucket forwards trace
        # through the op-lowering seam, and this LRU outlives an
        # MXNET_PALLAS flip — the escape hatch must recompile, not
        # serve the stale lowering
        return ("serve", self.name, bucket, sig,
                str(self._cdt) if self._cdt is not None else None,
                _pallas_dispatch.fingerprint())

    def _build_forward(self, bucket):
        """Pure ``fwd(params, aux, inputs)`` for one bucket: the
        ``deploy.py`` DAG walk, with params/aux as *arguments* instead
        of baked constants."""
        symbol = self._symbol
        nodes = symbol._nodes()
        head = [(id(n), oi) for n, oi in symbol._outputs]
        aux_names = symbol.list_auxiliary_states()
        aux_set = set(aux_names)
        aux_order = {n: i for i, n in enumerate(aux_names)}
        shapes = {n: (bucket,) + self._input_tails[n]
                  for n in self._input_names}
        arg_shapes, _, _ = symbol.infer_shape_partial(**shapes)
        zero_shapes = {}
        for n, s in zip(symbol.list_arguments(), arg_shapes):
            if n in self._zero_args:
                if s is None:
                    raise MXNetError(
                        "argument %r is neither an input nor in the "
                        "params and its shape cannot be inferred" % n)
                zero_shapes[n] = tuple(s)
        from ..executor import shape_overrides
        known = dict(shapes)
        known.update({n: tuple(a.shape) for n, a in self._params.items()})
        overrides = shape_overrides(symbol, known)
        cdt = self._cdt
        input_set = set(self._input_names)

        def fwd(params, aux, inputs):
            vals = {}
            for node in nodes:
                if node.is_variable:
                    nm = node.name
                    if nm in aux_set:
                        v = aux[aux_order[nm]]
                    elif nm in input_set:
                        v = inputs[nm]
                        if cdt is not None and v.dtype != cdt and \
                                jnp.issubdtype(v.dtype, jnp.floating):
                            v = v.astype(cdt)
                    elif nm in zero_shapes:
                        v = jnp.zeros(zero_shapes[nm],
                                      cdt or jnp.float32)
                    else:
                        v = params[nm]
                    vals[(id(node), 0)] = v
                    continue
                ins = [vals[(id(s), oi)] for s, oi in node.arg_inputs()]
                aux_in = tuple(vals[(id(s), oi)]
                               for s, oi in node.aux_inputs())
                outs, _ = node.op.apply(
                    overrides.get(id(node), node.attrs), ins, aux_in,
                    False, None)
                for oi, o in enumerate(outs):
                    vals[(id(node), oi)] = o
            outs = tuple(vals[k] for k in head)
            if cdt is not None:
                outs = tuple(
                    o.astype(jnp.float32)
                    if jnp.issubdtype(o.dtype, jnp.floating)
                    and o.dtype != jnp.float32 else o
                    for o in outs)
            return outs

        return fwd

    def _compile(self, bucket):
        tic = time.perf_counter()
        fwd = self._build_forward(bucket)
        # AOT specs carry the placement: without it the executable
        # compiles for the default device and rejects device-pinned
        # params at call time
        sh = (jax.sharding.SingleDeviceSharding(self._device)
              if self._device is not None else None)
        spec = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sh),
            (self._params, self._aux))
        in_spec = {n: jax.ShapeDtypeStruct(
            (bucket,) + self._input_tails[n],
            jnp.dtype(self._input_dtypes[n]), sharding=sh)
            for n in self._input_names}
        compiled = jax.jit(fwd).lower(spec[0], spec[1], in_spec).compile()
        out_avals = jax.eval_shape(fwd, spec[0], spec[1], in_spec)
        flags = tuple(len(o.shape) > 0 and o.shape[0] == bucket
                      for o in out_avals)
        ms = (time.perf_counter() - tic) * 1e3
        return _Program(compiled, bucket, flags, ms)

    def _acquire(self, bucket):
        """LRU lookup/compile for one bucket (cached_op.acquire shape:
        compile outside the lock, re-check for a race on insert)."""
        key = self._key(bucket)
        with self._lock:
            prog = self._programs.get(key)
            if prog is not None:
                self._programs.move_to_end(key)
                self._stats["hits"] += 1
                return prog
        prog = self._compile(bucket)
        with self._lock:
            raced = self._programs.get(key)
            if raced is not None:
                self._stats["hits"] += 1
                return raced
            self._stats["compiles"] += 1
            self._stats["compile_ms_total"] += prog.compile_ms
            while len(self._programs) >= self.max_programs:
                self._programs.popitem(last=False)
                self._stats["evictions"] += 1
            self._programs[key] = prog
            return prog

    def warmup(self, execute=True):
        """Compile — and by default EXECUTE once on zeros — every
        configured bucket ahead of traffic (warmup-at-load).  The
        execution matters: a freshly compiled XLA executable pays
        tens of ms of one-time setup (buffer/thread-pool init) on its
        first run, which must not land inside a served request.
        Returns the per-bucket compile times (ms)."""
        out = {}
        for b in self._edges:
            prog = self._acquire(b)
            out[b] = prog.compile_ms
            if execute:
                feed = {n: np.zeros((b,) + self._input_tails[n],
                                    self._input_dtypes[n])
                        for n in self._input_names}
                jax.block_until_ready(
                    prog.fn(self._params, self._aux, feed))
        return out

    # -- execution -----------------------------------------------------
    @hot_path
    def run(self, inputs, n=None, slice_outputs=True):
        """Run ``n`` rows of canonicalized inputs through the bucketed
        program.  Returns ``(outputs, bucket, batch_major)``:
        batch-major outputs come sliced back to ``n`` rows (device-side
        lazy slice, no host sync); ``batch_major`` flags which outputs
        carry a leading batch axis.  ``slice_outputs=False`` returns
        the raw bucket-shaped outputs (pad rows included) — the
        scheduler uses it because it re-slices per request anyway, and
        the intermediate ``[:n]`` would compile one XLA slice program
        per distinct row count.  Called from the serving engine's
        dispatch loop — everything here is enqueue-only device work
        plus cheap host padding."""
        if n is None:
            n = int(inputs[self._input_names[0]].shape[0])
        bucket = bucket_for(n, self._edges)
        if bucket is None:
            raise MXNetError(
                "request of %d rows exceeds the largest serving bucket "
                "(%d)" % (n, self._edges[-1]))
        prog = self._acquire(bucket)
        feed = {}
        for name in self._input_names:
            v = inputs[name]
            if v.shape[0] != bucket:
                pad = np.zeros((bucket,) + tuple(v.shape[1:]), v.dtype)
                pad[:n] = v
                v = pad
            feed[name] = v
        outs = prog.fn(self._params, self._aux, feed)
        if slice_outputs:
            outs = [o[:n] if bm and n != bucket else o
                    for o, bm in zip(outs, prog.out_batch_major)]
        else:
            outs = list(outs)
        return outs, bucket, prog.out_batch_major

    # -- introspection -------------------------------------------------
    def stats(self):
        """Compile-cache stats: hits/compiles/evictions/size plus the
        currently-resident buckets."""
        with self._lock:
            out = dict(self._stats)
            out["size"] = len(self._programs)
            out["max_programs"] = self.max_programs
            out["buckets_resident"] = sorted(
                p.bucket for p in self._programs.values())
        out["edges"] = list(self._edges)
        out["compute_dtype"] = str(self._cdt) if self._cdt else None
        return out

    def reset_stats(self):
        with self._lock:
            for k in ("hits", "compiles", "evictions"):
                self._stats[k] = 0
            self._stats["compile_ms_total"] = 0.0

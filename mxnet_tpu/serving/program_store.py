"""AOT program store: compiled-ahead-of-time inference per shape bucket.

The training side compiles lazily (``cached_op.py``'s tiered LRU,
``executor.py``'s bind-time jit) because training shapes are stable after
step one.  A serving process is the opposite regime: request sizes vary
per call and the first request of a new shape must NOT pay a multi-second
XLA compile.  So the store

* quantizes request batch sizes into configured **bucket edges**
  (``MXNET_SERVE_BUCKETS``): a request of ``n`` rows is zero-padded up to
  the smallest edge ``>= n``, runs the bucket's program, and the pad rows
  are sliced back off every batch-major output.  Inference graphs are
  row-independent (``is_train=False`` — BatchNorm reads running stats,
  softmax is per-row), so the pad rows cannot perturb the real rows and
  fp32 bucketed outputs are **bit-equal** to an unbatched forward
  (pinned by ``tests/test_serving.py``);
* compiles each bucket's program **ahead of time** —
  ``jax.jit(fwd).lower(specs...).compile()`` — normally at model load
  (:meth:`ProgramStore.warmup`), so steady-state dispatch never traces;
* holds the executables in a bounded LRU keyed like ``cached_op.py``'s
  (``(model, bucket, input avals, dtype)``), ``MXNET_SERVE_PROGRAM_CACHE``
  entries, with hit/compile/eviction stats.

Parameters are **arguments** of the compiled programs (not baked
constants like ``deploy.py``'s export), so all buckets share one
device-resident copy of the weights and a model upgrade swaps arrays
without recompiling.  ``compute_dtype='bfloat16'`` casts the floating
weights once at load (half the serving memory) and casts inputs inside
the program; ``compute_dtype='int8'`` quantizes the FullyConnected
weights once at load into ``(int8 codes, fp32 scales)`` pairs (~4x
less resident weight memory — ``stats()["weight_bytes"]`` measures it)
that dequantize INSIDE the programs through the fused dequant-matmul
door (``pallas_ops/dequant_matmul.py``; dense XLA twin off the kernel
route); outputs always come back float32.
"""
from __future__ import annotations

import logging
import time
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np

from .. import metrics as _metrics
from ..analysis.lockcheck import make_lock
from ..base import MXNetError, get_env, hot_path
from ..pallas_ops import dispatch as _pallas_dispatch

def _cache_event(event):
    """Process-wide program-cache counter (every store feeds it; the
    per-store split stays on each store's stats() tree)."""
    return _metrics.cached_counter(
        "serve_program_cache_%s_total" % event,
        help="AOT serving-program LRU events across all stores")
from ..pallas_ops.dequant_matmul import QuantizedWeight, quantize_int8

__all__ = ["ProgramStore", "GenerativeProgramStore", "bucket_edges",
           "bucket_for", "sample_tokens", "sample_tokens_p",
           "spec_verify", "host_sample"]

log = logging.getLogger(__name__)


def bucket_edges(edges=None, env_var="MXNET_SERVE_BUCKETS"):
    """Resolve bucket edges: an explicit iterable, or the ``env_var``
    comma list (batch buckets by default; the prefill programs pass
    ``MXNET_SERVE_PROMPT_BUCKETS``); returned sorted, deduplicated,
    all positive."""
    if edges is None:
        raw = get_env(env_var)
        edges = [int(tok) for tok in str(raw).split(",") if tok.strip()]
    out = sorted({int(e) for e in edges})
    if not out or out[0] < 1:
        raise MXNetError("serving bucket edges must be positive ints, "
                         "got %r" % (edges,))
    return tuple(out)


def bucket_for(n, edges):
    """Smallest edge >= n, or None when n exceeds the largest edge."""
    for e in edges:
        if n <= e:
            return e
    return None


def _as_device_array(v):
    """Model parameter -> jax array WITHOUT a host round-trip when the
    value is already device-resident (NDArray / jax.Array)."""
    data = getattr(v, "_data", v)  # NDArray unwraps; numpy/jax pass through
    return data if isinstance(data, jax.Array) else jnp.asarray(data)


def _fc_weight_only_params(symbol):
    """Variables consumed EXCLUSIVELY as FullyConnected weight inputs —
    the int8-quantizable set of a symbol graph.  Any other consumer
    (a norm, an elementwise op, an output head) would receive the
    ``(codes, scales)`` pair it does not understand, so shared
    variables stay full precision."""
    fc_w, other = set(), set()
    for node in symbol._nodes():
        if node.is_variable:
            continue
        is_fc = node.op.name == "FullyConnected"
        for idx, (s, _oi) in enumerate(node.arg_inputs()):
            if s.is_variable:
                (fc_w if is_fc and idx == 1 else other).add(s.name)
    for n, _oi in symbol._outputs:
        if n.is_variable:
            other.add(n.name)
    return fc_w - other


def _weight_bytes(tree):
    """Resident bytes of a param/aux pytree grouped by storage dtype —
    the measurement behind the int8 ~4x / bf16 2x weight-memory claims
    (``stats()["weight_bytes"]``; the bench rows read this instead of
    recomputing)."""
    by_dtype = {}
    for leaf in jax.tree_util.tree_leaves(tree):
        dt = str(leaf.dtype)
        by_dtype[dt] = by_dtype.get(dt, 0) + \
            int(leaf.size) * int(leaf.dtype.itemsize)
    return {"total": sum(by_dtype.values()), "by_dtype": by_dtype}


# ---------------------------------------------------------------------------
# Token sampling: ONE pure function for both serving modes.
# ---------------------------------------------------------------------------
def sample_tokens(logits, keys, temps, top_ks):
    """One sampling step over a ``(S, V)`` logits batch.

    Per slot: ``temps[s] <= 0`` is greedy (argmax); otherwise seeded
    temperature sampling over the ``top_ks[s]`` highest logits
    (``top_ks[s] <= 0`` = full vocab) via ``jax.random.categorical``.
    ``keys`` is the per-slot threefry key data ``(S, 2) uint32``, split
    once per step (counter-based, so the stream is a pure function of
    the request seed and the step index); returns ``(tokens (S,) int32,
    new_keys (S, 2))``.

    PURE and shared: the SAME body traces into the ``decode_sample``
    program (in-graph sampling, ``MXNET_SERVE_SAMPLE=graph``) and jits
    standalone over host-fetched logits for the ``host`` escape hatch —
    identical ops on identical values, so the two modes emit
    byte-identical token streams from the same seeds (pinned)."""
    logits = jnp.asarray(logits, jnp.float32)
    n_vocab = logits.shape[-1]
    keys = jnp.asarray(keys, jnp.uint32)
    temps = jnp.asarray(temps, jnp.float32)
    top_ks = jnp.asarray(top_ks, jnp.int32)
    pairs = jax.vmap(jax.random.split)(keys)        # (S, 2, 2)
    carry, use = pairs[:, 0], pairs[:, 1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    z = logits / jnp.maximum(temps, 1e-6)[:, None]
    k = jnp.clip(jnp.where(top_ks <= 0, n_vocab, top_ks), 1, n_vocab)
    kth = jnp.take_along_axis(-jnp.sort(-z, axis=-1),
                              (k - 1)[:, None], axis=-1)
    z = jnp.where(z >= kth, z, -jnp.inf)
    sampled = jax.vmap(jax.random.categorical)(use, z).astype(jnp.int32)
    return jnp.where(temps <= 0.0, greedy, sampled), carry


# the host escape hatch's sampler: the same function, jitted standalone
# (jax re-specializes per logits shape; the decode engine calls it on
# the fetched (slots, vocab) matrix)
host_sample = jax.jit(sample_tokens)


def _masked_dist(logits, temps, top_ks):
    """The categorical distribution :func:`sample_tokens` draws from,
    as explicit probabilities over ``(S, V)`` rows: temperature + top-k
    masked softmax (``jax.random.categorical`` over masked ``z`` IS
    ``softmax(z)``); greedy rows (``temps <= 0``) are one-hot at the
    argmax.  The speculative plane's shared density: the draft's
    proposal distribution q and the target's acceptance distribution p
    both come from THIS function on their respective logits, so the
    rejection rule compares exactly the densities the two samplers
    use."""
    logits = jnp.asarray(logits, jnp.float32)
    n_vocab = logits.shape[-1]
    temps = jnp.asarray(temps, jnp.float32)
    top_ks = jnp.asarray(top_ks, jnp.int32)
    z = logits / jnp.maximum(temps, 1e-6)[:, None]
    k = jnp.clip(jnp.where(top_ks <= 0, n_vocab, top_ks), 1, n_vocab)
    kth = jnp.take_along_axis(-jnp.sort(-z, axis=-1),
                              (k - 1)[:, None], axis=-1)
    z = jnp.where(z >= kth, z, -jnp.inf)
    probs = jax.nn.softmax(z, axis=-1)
    onehot = jax.nn.one_hot(jnp.argmax(logits, axis=-1), n_vocab,
                            dtype=jnp.float32)
    return jnp.where((temps <= 0.0)[:, None], onehot, probs)


def sample_tokens_p(logits, keys, temps, top_ks):
    """:func:`sample_tokens` that ALSO returns the per-slot proposal
    distribution ``q (S, V)`` the token was drawn from — the draft
    model's sampling step in speculative decoding (the verify program
    needs q(d) for the acceptance test ``u * q(d) <= p(d)``).  Returns
    ``(tokens, new_keys, q)``; token/key behavior is byte-identical to
    :func:`sample_tokens`."""
    toks, carry = sample_tokens(logits, keys, temps, top_ks)
    return toks, carry, _masked_dist(logits, temps, top_ks)


def spec_verify(logits_all, prop_toks, prop_q, keys, temps, top_ks,
                valid):
    """In-graph speculative accept/reject (standard rejection-sampling
    rule) over one verify step's logits.

    logits_all: (B, K+1, V) fp32 — the target's logits at the K+1
    verified positions (row j conditions on the prompt + the first j
    draft tokens); prop_toks: (B, K) int32 draft proposals; prop_q:
    (B, K, V) fp32 — the draft's proposal distribution for each
    proposal (:func:`sample_tokens_p`); keys: (B, 2) uint32 per-slot
    threefry chains; valid: (B,) int32 — row b verifies
    ``valid[b] - 1`` proposals (``1 <= valid <= K+1``; a row's window
    shrinks near its token budget).

    Per slot: greedy rows (``temps <= 0``) accept the longest prefix of
    proposals matching the target argmax and emit the target argmax at
    the first mismatch — byte-identical to non-speculative greedy
    decoding.  Sampled rows draw one uniform per position off the
    slot's key chain and accept proposal j iff ``u_j * q_j(d_j) <=
    p_j(d_j)``; the first rejection resamples from the corrected
    residual ``max(p - q, 0)`` (renormalized; p itself when the
    residual vanishes, i.e. q covers p), and a fully-accepted window
    draws the bonus token directly from p — the classic proof gives
    token streams DISTRIBUTION-identical to sampling from p alone.

    Returns ``(out_toks (B, K+1) int32, n_emit (B,) int32, new_keys
    (B, 2))``: row b emits ``out_toks[b, :n_emit[b]]`` (accepted
    proposals + the corrected/bonus token), ``1 <= n_emit <= valid``."""
    logits_all = jnp.asarray(logits_all, jnp.float32)
    B, K1, V = logits_all.shape
    K = K1 - 1
    prop_toks = jnp.asarray(prop_toks, jnp.int32)
    prop_q = jnp.asarray(prop_q, jnp.float32)
    keys = jnp.asarray(keys, jnp.uint32)
    temps = jnp.asarray(temps, jnp.float32)
    top_ks = jnp.asarray(top_ks, jnp.int32)
    valid = jnp.asarray(valid, jnp.int32)
    # per-slot chain: carry + K accept draws + 1 resample draw (one
    # split per verify keeps the chain counter-based like sample_tokens)
    allk = jax.vmap(lambda kk: jax.random.split(kk, K + 2))(keys)
    carry, res_keys = allk[:, 0], allk[:, K + 1]
    p_full = _masked_dist(
        logits_all.reshape(B * K1, V), jnp.repeat(temps, K1),
        jnp.repeat(top_ks, K1)).reshape(B, K1, V)
    greedy_all = jnp.argmax(logits_all, axis=-1).astype(jnp.int32)
    rows = jnp.arange(B)
    if K:
        acc_keys = allk[:, 1:K + 1].reshape(B * K, 2)
        u = jax.vmap(jax.random.uniform)(acc_keys).reshape(B, K)
        pd = jnp.take_along_axis(p_full[:, :K], prop_toks[..., None],
                                 -1)[..., 0]
        qd = jnp.take_along_axis(prop_q, prop_toks[..., None],
                                 -1)[..., 0]
        acc = jnp.where((temps <= 0.0)[:, None],
                        prop_toks == greedy_all[:, :K],
                        u * qd <= pd)
        acc = acc & (jnp.arange(K, dtype=jnp.int32)[None, :] + 1 <
                     valid[:, None])
        a = jnp.sum(jnp.cumprod(acc.astype(jnp.int32), axis=1), axis=1)
    else:  # pragma: no cover - K=0 degenerates to a plain sample
        a = jnp.zeros((B,), jnp.int32)
    p_a = p_full[rows, a]                                   # (B, V)
    q_ext = jnp.concatenate(
        [prop_q, jnp.zeros((B, 1, V), jnp.float32)], axis=1)
    # the bonus position (full accept, a == valid-1) has no proposal:
    # its residual is p itself
    q_a = jnp.where((a >= valid - 1)[:, None], 0.0, q_ext[rows, a])
    res = jnp.maximum(p_a - q_a, 0.0)
    tot = jnp.sum(res, axis=-1, keepdims=True)
    res = jnp.where(tot > 0.0, res / jnp.where(tot > 0.0, tot, 1.0),
                    p_a)
    sampled = jax.vmap(jax.random.categorical)(
        res_keys, jnp.log(jnp.maximum(res, 1e-30))).astype(jnp.int32)
    corrected = jnp.where(temps <= 0.0, greedy_all[rows, a], sampled)
    out = jnp.concatenate([prop_toks, jnp.zeros((B, 1), jnp.int32)],
                          axis=1)
    out = out.at[rows, a].set(corrected)
    return out, (a + 1).astype(jnp.int32), carry


class _Program:
    __slots__ = ("fn", "bucket", "out_batch_major", "compile_ms")

    def __init__(self, fn, bucket, out_batch_major, compile_ms):
        self.fn = fn
        self.bucket = bucket
        self.out_batch_major = out_batch_major
        self.compile_ms = compile_ms


class ProgramStore:
    """Bucketed AOT-compiled inference programs for one model.

    Parameters
    ----------
    symbol : Symbol
        The inference graph.
    arg_params, aux_params : dict
        name -> array (NDArray / jax / numpy).  Non-input arguments
        missing from ``arg_params`` whose shape is inferable are baked
        as zeros (unused loss-head labels, same policy as ``deploy.py``).
    input_shapes : dict
        name -> full shape; axis 0 of every input is the batch axis the
        store buckets on (the leading dim given here is only a shape
        template — requests of any bucketable size are accepted).
    name : str
        Cache-key / diagnostics tag.
    compute_dtype : str, optional
        ``'bfloat16'`` casts floating weights once at load and inputs
        inside the program; ``'int8'`` quantizes the FC weights once at
        load (scale-per-row symmetric, ``quantize_int8``) into
        ``(codes, scales)`` program arguments that dequantize in-graph
        through the fused dequant-matmul door; outputs return float32
        either way.  None = master dtype (fp32 bit-equal serving).
    buckets : iterable of int, optional
        Bucket edges; overrides ``MXNET_SERVE_BUCKETS``.
    max_programs : int, optional
        LRU bound; overrides ``MXNET_SERVE_PROGRAM_CACHE``.
    input_dtypes : dict, optional
        name -> numpy dtype of the wire inputs (default float32).
    device : jax.Device, optional
        Pin weights (and hence the compiled programs, which follow
        their committed arguments) to this device; default leaves
        placement to jax's default device.
    """

    def __init__(self, symbol, arg_params, aux_params, input_shapes,
                 name="model", compute_dtype=None, buckets=None,
                 max_programs=None, input_dtypes=None, device=None):
        self._symbol = symbol
        self.name = name
        self._edges = bucket_edges(buckets)
        self._quant8 = str(compute_dtype).lower() == "int8" \
            if compute_dtype else False
        self._cdt = (None if self._quant8 or not compute_dtype
                     else jnp.dtype(compute_dtype))
        # cache-key / stats tag for the serving dtype (int8 has no jnp
        # compute dtype — activations stay fp32, weights are codes)
        self._dtype_tag = ("int8" if self._quant8 else
                           str(self._cdt) if self._cdt is not None
                           else None)
        self._input_names = list(input_shapes)
        if not self._input_names:
            raise MXNetError("serving needs at least one input")
        self._input_tails = {n: tuple(input_shapes[n])[1:]
                             for n in self._input_names}
        self._input_dtypes = {
            n: np.dtype((input_dtypes or {}).get(n, "float32"))
            for n in self._input_names}
        self._device = device
        # bucketing correctness requires every output to carry a leading
        # batch axis: pad rows are sliced off outputs, and the batcher
        # hands each request its row range — an output computed over the
        # WHOLE batch (a mean/sum head) would mix pad rows and, under
        # continuous batching, other requests' rows into every result.
        # Probe the symbol at two distinct batch sizes: batch-major
        # outputs track the batch, anything else is rejected at load.
        out_names = symbol.list_outputs()
        probes = []
        for b in (self._edges[-1], self._edges[-1] + 1):
            probe = {n: (b,) + self._input_tails[n]
                     for n in self._input_names}
            _, out_shapes, _ = symbol.infer_shape_partial(**probe)
            probes.append(out_shapes)
        for i, oname in enumerate(out_names):
            s1, s2 = probes[0][i], probes[1][i]
            if s1 is None or s2 is None or not len(s1) or not len(s2) \
                    or s1[0] != self._edges[-1] \
                    or s2[0] != self._edges[-1] + 1:
                raise MXNetError(
                    "output %r of serving model %r is not batch-major "
                    "(shape %s at batch size %d): bucket padding and "
                    "continuous batching require row-independent "
                    "batch-major outputs — serve this model with the "
                    "classic Predictor instead"
                    % (oname, name, s1, self._edges[-1]))

        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        aux_params = aux_params or {}
        self._param_names = [n for n in arg_names
                             if n not in input_shapes and n in arg_params]
        self._zero_args = [n for n in arg_names
                           if n not in input_shapes
                           and n not in arg_params]

        # int8: quantize exactly the variables every consumer of which
        # is a FullyConnected WEIGHT input (the matmul door understands
        # the pair; nothing else does) — in an MLP/classifier head that
        # is the overwhelming share of the bytes
        self._quant_names = (_fc_weight_only_params(symbol)
                             if self._quant8 else frozenset())
        self._aux_names = list(aux_names)

        self._params = {n: self._load_param(arg_params[n], n)
                        for n in self._param_names}
        aux = []
        # aux states missing from the checkpoint keep predictor.py's
        # policy: zero-filled at their inferred shape
        shapes = {n: tuple(input_shapes[n]) for n in self._input_names}
        _, _, aux_shapes = symbol.infer_shape_partial(**shapes)
        for n, shape in zip(aux_names, aux_shapes):
            if n in aux_params:
                aux.append(self._load_param(aux_params[n]))
            elif shape is not None:
                z = jnp.zeros(tuple(shape), self._cdt or jnp.float32)
                aux.append(z if device is None
                           else jax.device_put(z, device))
            else:
                raise MXNetError("auxiliary state %r is not in the params "
                                 "and its shape cannot be inferred" % n)
        self._aux = tuple(aux)
        # the PUBLISHED weight set: dispatch reads this tuple exactly
        # once per run, so a hot swap (swap_params) is atomic per
        # request — every in-flight request executes against exactly
        # one (params, aux, version) snapshot, never a mix
        self._version = 1
        self._live = (self._params, self._aux, self._version)

        if max_programs is None:
            max_programs = int(get_env("MXNET_SERVE_PROGRAM_CACHE"))
        self.max_programs = max(1, int(max_programs))
        if self.max_programs < len(self._edges):
            # warmup can't keep every bucket resident: the LRU evicts
            # early buckets before traffic, and the first request for
            # one pays a compile AT DISPATCH — the stall AOT exists to
            # prevent.  Legal (eviction tests rely on it) but worth a
            # loud heads-up in a serving process.
            log.warning(
                "serving model %r: program cache (%d) is smaller than "
                "the bucket count (%d); warmed buckets will be evicted "
                "and recompile inside served requests — raise "
                "MXNET_SERVE_PROGRAM_CACHE or trim MXNET_SERVE_BUCKETS",
                name, self.max_programs, len(self._edges))
        self._programs = OrderedDict()   # key -> _Program
        self._lock = make_lock("serving.program_store")
        self._stats = {"hits": 0, "compiles": 0, "evictions": 0,
                       "compile_ms_total": 0.0}

    def _load_param(self, v, name=None):
        """One parameter through the serving dtype policy: int8-quantize
        the FC-weight-only set, cast floats to the compute dtype, pin to
        the store's device.  Shared by load-time and swap-time paths so
        a swapped weight set goes through EXACTLY the original
        pipeline."""
        a = _as_device_array(v)
        if name in self._quant_names and a.ndim == 2 and \
                jnp.issubdtype(a.dtype, jnp.floating):
            codes, scales = quantize_int8(np.asarray(a))
            c, s = jnp.asarray(codes), jnp.asarray(scales)
            if self._device is not None:
                c = jax.device_put(c, self._device)
                s = jax.device_put(s, self._device)
            return QuantizedWeight(c, s)
        if self._cdt is not None and a.dtype != self._cdt and \
                jnp.issubdtype(a.dtype, jnp.floating):
            a = a.astype(self._cdt)
        if self._device is not None:
            # committed params pin the compiled programs' placement
            # (uncommitted request inputs follow them)
            a = jax.device_put(a, self._device)
        return a

    # -- hot weight swap -----------------------------------------------
    def swap_params(self, arg_params, aux_params=None):
        """Atomically republish the device-resident weight arguments.

        ``arg_params`` must cover every non-input argument the store
        serves (same names/shapes/dtypes as the loaded checkpoint —
        the AOT programs were lowered against those avals and are NOT
        recompiled).  ``aux_params=None`` keeps the current auxiliary
        states.  The new set goes through the same dtype pipeline as
        load (bf16 cast / int8 quantization / device pinning), then ONE
        reference assignment publishes ``(params, aux, version)``;
        requests dispatched before the swap keep the old snapshot,
        requests after get the new one, and no request ever sees a mix
        (``run`` reads the snapshot exactly once).  Returns the new
        version (monotonic, reported by ``stats()['version']``)."""
        missing = [n for n in self._param_names if n not in arg_params]
        if missing:
            raise MXNetError("swap_params for %r is missing %s"
                             % (self.name, sorted(missing)))
        new_params = {}
        for n in self._param_names:
            a = self._load_param(arg_params[n], n)
            old = self._params[n]
            quant = isinstance(old, QuantizedWeight)
            if quant != isinstance(a, QuantizedWeight):
                pairs = None
            elif quant:
                pairs = ((a.codes, old.codes), (a.scales, old.scales))
            else:
                pairs = ((a, old),)
            if pairs is None or any(
                    x.shape != y.shape or x.dtype != y.dtype
                    for x, y in pairs):
                raise MXNetError(
                    "swap_params for %r: parameter %r does not match "
                    "the compiled programs' signature (the serving "
                    "programs are not recompiled on swap)" % (self.name,
                                                              n))
            new_params[n] = a
        if aux_params is None:
            new_aux = self._aux
        else:
            new_aux = []
            for n, old in zip(self._aux_names, self._aux):
                if n not in aux_params:
                    new_aux.append(old)
                    continue
                a = self._load_param(aux_params[n])
                if a.shape != old.shape or a.dtype != old.dtype:
                    raise MXNetError(
                        "swap_params for %r: auxiliary state %r does "
                        "not match the compiled programs' signature"
                        % (self.name, n))
                new_aux.append(a)
            new_aux = tuple(new_aux)
        with self._lock:
            self._params = new_params
            self._aux = new_aux
            self._version += 1
            # single reference assignment = the atomic publish point
            self._live = (new_params, new_aux, self._version)
        return self._version

    @property
    def version(self):
        return self._version

    def param_snapshot(self):
        """Opaque handle to the live weight set, for
        :meth:`restore_params`.  The rolling weight swap captures one
        per replica before swapping so a failed re-probe can roll the
        already-swapped replicas back to exactly the weights they
        served (device-resident, already through the dtype pipeline)."""
        params, aux, _ = self._live
        return (params, aux)

    def restore_params(self, snap):
        """Atomically republish a :meth:`param_snapshot` — the
        rolling-swap ABORT path.  No dtype pipeline and no signature
        check (the snapshot came from this store).  Bumps the version
        like any swap: versions stay monotonic even when the weights
        roll back, so 'version changed' remains a reliable swap
        witness."""
        params, aux = snap
        with self._lock:
            self._params = dict(params)
            self._aux = aux
            self._version += 1
            self._live = (self._params, self._aux, self._version)
        return self._version

    # -- geometry ------------------------------------------------------
    @property
    def edges(self):
        return self._edges

    def max_bucket(self):
        return self._edges[-1]

    @property
    def input_names(self):
        return list(self._input_names)

    def output_names(self):
        return self._symbol.list_outputs()

    def canon_inputs(self, inputs):
        """Validate + canonicalize one request's inputs (client-thread
        work: np conversion, dtype cast, shape checks).  Returns
        ``(dict name -> np.ndarray, n_rows)``."""
        got, want = set(inputs), set(self._input_names)
        if got != want:
            raise MXNetError("serving inputs mismatch for %r: got %s, "
                             "want %s" % (self.name, sorted(got),
                                          sorted(want)))
        out = {}
        n = None
        for name in self._input_names:
            a = np.asarray(inputs[name], dtype=self._input_dtypes[name])
            tail = self._input_tails[name]
            if a.ndim != len(tail) + 1 or tuple(a.shape[1:]) != tail:
                raise MXNetError(
                    "input %r has shape %s; want (n,%s)"
                    % (name, a.shape, ",".join(map(str, tail))))
            if n is None:
                n = int(a.shape[0])
            elif int(a.shape[0]) != n:
                raise MXNetError("inputs disagree on batch rows: %d vs %d"
                                 % (n, a.shape[0]))
            out[name] = a
        if n < 1:
            raise MXNetError("empty request (0 rows)")
        if bucket_for(n, self._edges) is None:
            raise MXNetError(
                "request of %d rows exceeds the largest serving bucket "
                "(%d); raise MXNET_SERVE_BUCKETS or split the request"
                % (n, self._edges[-1]))
        return out, n

    # -- compilation ---------------------------------------------------
    def _key(self, bucket):
        sig = tuple((n, (bucket,) + self._input_tails[n],
                     str(self._input_dtypes[n]))
                    for n in self._input_names)
        # the Pallas dispatch fingerprint rides in the key like in the
        # cached-op and SPMD program caches: bucket forwards trace
        # through the op-lowering seam, and this LRU outlives an
        # MXNET_PALLAS flip — the escape hatch must recompile, not
        # serve the stale lowering
        return ("serve", self.name, bucket, sig, self._dtype_tag,
                _pallas_dispatch.fingerprint())

    def _build_forward(self, bucket):
        """Pure ``fwd(params, aux, inputs)`` for one bucket: the
        ``deploy.py`` DAG walk, with params/aux as *arguments* instead
        of baked constants."""
        symbol = self._symbol
        nodes = symbol._nodes()
        head = [(id(n), oi) for n, oi in symbol._outputs]
        aux_names = symbol.list_auxiliary_states()
        aux_set = set(aux_names)
        aux_order = {n: i for i, n in enumerate(aux_names)}
        shapes = {n: (bucket,) + self._input_tails[n]
                  for n in self._input_names}
        arg_shapes, _, _ = symbol.infer_shape_partial(**shapes)
        zero_shapes = {}
        for n, s in zip(symbol.list_arguments(), arg_shapes):
            if n in self._zero_args:
                if s is None:
                    raise MXNetError(
                        "argument %r is neither an input nor in the "
                        "params and its shape cannot be inferred" % n)
                zero_shapes[n] = tuple(s)
        from ..executor import shape_overrides
        known = dict(shapes)
        known.update({n: tuple(a.shape) for n, a in self._params.items()})
        overrides = shape_overrides(symbol, known)
        cdt = self._cdt
        input_set = set(self._input_names)

        def fwd(params, aux, inputs):
            vals = {}
            for node in nodes:
                if node.is_variable:
                    nm = node.name
                    if nm in aux_set:
                        v = aux[aux_order[nm]]
                    elif nm in input_set:
                        v = inputs[nm]
                        if cdt is not None and v.dtype != cdt and \
                                jnp.issubdtype(v.dtype, jnp.floating):
                            v = v.astype(cdt)
                    elif nm in zero_shapes:
                        v = jnp.zeros(zero_shapes[nm],
                                      cdt or jnp.float32)
                    else:
                        v = params[nm]
                    vals[(id(node), 0)] = v
                    continue
                ins = [vals[(id(s), oi)] for s, oi in node.arg_inputs()]
                aux_in = tuple(vals[(id(s), oi)]
                               for s, oi in node.aux_inputs())
                outs, _ = node.op.apply(
                    overrides.get(id(node), node.attrs), ins, aux_in,
                    False, None)
                for oi, o in enumerate(outs):
                    vals[(id(node), oi)] = o
            outs = tuple(vals[k] for k in head)
            if cdt is not None:
                outs = tuple(
                    o.astype(jnp.float32)
                    if jnp.issubdtype(o.dtype, jnp.floating)
                    and o.dtype != jnp.float32 else o
                    for o in outs)
            return outs

        return fwd

    def _compile(self, bucket):
        tic = time.perf_counter()
        fwd = self._build_forward(bucket)
        # AOT specs carry the placement: without it the executable
        # compiles for the default device and rejects device-pinned
        # params at call time
        sh = (jax.sharding.SingleDeviceSharding(self._device)
              if self._device is not None else None)
        spec = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sh),
            (self._params, self._aux))
        in_spec = {n: jax.ShapeDtypeStruct(
            (bucket,) + self._input_tails[n],
            jnp.dtype(self._input_dtypes[n]), sharding=sh)
            for n in self._input_names}
        compiled = jax.jit(fwd).lower(spec[0], spec[1], in_spec).compile()
        out_avals = jax.eval_shape(fwd, spec[0], spec[1], in_spec)
        flags = tuple(len(o.shape) > 0 and o.shape[0] == bucket
                      for o in out_avals)
        ms = (time.perf_counter() - tic) * 1e3
        return _Program(compiled, bucket, flags, ms)

    def _acquire(self, bucket):
        """LRU lookup/compile for one bucket (cached_op.acquire shape:
        compile outside the lock, re-check for a race on insert)."""
        key = self._key(bucket)
        with self._lock:
            prog = self._programs.get(key)
            if prog is not None:
                self._programs.move_to_end(key)
                self._stats["hits"] += 1
                _cache_event("hits").inc()
                return prog
        prog = self._compile(bucket)
        with self._lock:
            raced = self._programs.get(key)
            if raced is not None:
                self._stats["hits"] += 1
                _cache_event("hits").inc()
                return raced
            self._stats["compiles"] += 1
            self._stats["compile_ms_total"] += prog.compile_ms
            _cache_event("compiles").inc()
            while len(self._programs) >= self.max_programs:
                self._programs.popitem(last=False)
                self._stats["evictions"] += 1
                _cache_event("evictions").inc()
            self._programs[key] = prog
            return prog

    def warmup(self, execute=True):
        """Compile — and by default EXECUTE once on zeros — every
        configured bucket ahead of traffic (warmup-at-load).  The
        execution matters: a freshly compiled XLA executable pays
        tens of ms of one-time setup (buffer/thread-pool init) on its
        first run, which must not land inside a served request.
        Returns the per-bucket compile times (ms)."""
        out = {}
        for b in self._edges:
            prog = self._acquire(b)
            out[b] = prog.compile_ms
            if execute:
                feed = {n: np.zeros((b,) + self._input_tails[n],
                                    self._input_dtypes[n])
                        for n in self._input_names}
                params, aux, _v = self._live
                jax.block_until_ready(prog.fn(params, aux, feed))
        return out

    # -- execution -----------------------------------------------------
    @hot_path
    def run(self, inputs, n=None, slice_outputs=True):
        """Run ``n`` rows of canonicalized inputs through the bucketed
        program.  Returns ``(outputs, bucket, batch_major)``:
        batch-major outputs come sliced back to ``n`` rows (device-side
        lazy slice, no host sync); ``batch_major`` flags which outputs
        carry a leading batch axis.  ``slice_outputs=False`` returns
        the raw bucket-shaped outputs (pad rows included) — the
        scheduler uses it because it re-slices per request anyway, and
        the intermediate ``[:n]`` would compile one XLA slice program
        per distinct row count.  Called from the serving engine's
        dispatch loop — everything here is enqueue-only device work
        plus cheap host padding."""
        if n is None:
            n = int(inputs[self._input_names[0]].shape[0])
        bucket = bucket_for(n, self._edges)
        if bucket is None:
            raise MXNetError(
                "request of %d rows exceeds the largest serving bucket "
                "(%d)" % (n, self._edges[-1]))
        prog = self._acquire(bucket)
        feed = {}
        for name in self._input_names:
            v = inputs[name]
            if v.shape[0] != bucket:
                pad = np.zeros((bucket,) + tuple(v.shape[1:]), v.dtype)
                pad[:n] = v
                v = pad
            feed[name] = v
        # ONE read of the published (params, aux, version) snapshot:
        # the hot-swap atomicity guarantee — this request runs entirely
        # against one weight version
        params, aux, _v = self._live
        outs = prog.fn(params, aux, feed)
        if slice_outputs:
            outs = [o[:n] if bm and n != bucket else o
                    for o, bm in zip(outs, prog.out_batch_major)]
        else:
            outs = list(outs)
        return outs, bucket, prog.out_batch_major

    # -- introspection -------------------------------------------------
    def stats(self):
        """Compile-cache stats: hits/compiles/evictions/size plus the
        currently-resident buckets."""
        with self._lock:
            out = dict(self._stats)
            out["size"] = len(self._programs)
            out["max_programs"] = self.max_programs
            out["buckets_resident"] = sorted(
                p.bucket for p in self._programs.values())
        out["edges"] = list(self._edges)
        out["compute_dtype"] = self._dtype_tag
        params, aux, version = self._live
        out["version"] = version
        out["weight_bytes"] = _weight_bytes((params, aux))
        return out

    def reset_stats(self):
        with self._lock:
            for k in ("hits", "compiles", "evictions"):
                self._stats[k] = 0
            self._stats["compile_ms_total"] = 0.0


def cache_donate_argnums(nums):
    """Donate the KV-cache arguments off-CPU only — PJRT:CPU has no
    donation (the same never-on-CPU guard as the training planes'
    donation seams; donating there only warns, once per compiled
    bucket).  Callers rebind their cache references to the program
    outputs either way, so behavior is identical."""
    return () if jax.default_backend() == "cpu" else tuple(nums)


# ---------------------------------------------------------------------------
# Generative (autoregressive) program store: the prefill/decode split.
#
# A generation workload is two programs, not one.  PREFILL runs a
# padded prompt batch once, fills the KV cache and emits the logits the
# first generated token samples from; DECODE consumes ONE token per
# sequence against the cache.  Both are AOT-compiled and warmed exactly
# like the forward store's bucket programs, with the program key space
#
#   prefill: (batch-bucket, prompt-bucket)   -> cache sized for the bucket
#   decode:  (batch-bucket, cache-bucket)    -> cache bucket = a multiple
#                                               of MXNET_SERVE_KV_BLOCK
#
# so arbitrary request shapes and growing sequences hit a small fixed
# set of executables.  The KV cache itself is SERVING STATE living
# beside the params (one device-resident copy, owned by whoever drives
# the programs — the GenerationEngine attaches its live state here for
# introspection); the programs stay pure — cache in, updated cache out —
# with both cache arguments DONATED, so the per-step update lowers to an
# in-place dynamic_update_slice on the resident buffers.
# ---------------------------------------------------------------------------
class GenerativeProgramStore:
    """AOT prefill/decode programs for one autoregressive LM.

    Parameters
    ----------
    params : dict
        name -> array, the ``transformer_lm`` symbol graph's trained
        arguments (``embed_weight``, ``blk*_*``, ``final_ln_*``,
        ``pred_*``).
    spec : dict
        ``transformer_lm.lm_spec(...)`` architecture spec.
    batch_buckets / prompt_buckets : iterable of int, optional
        Bucket edges; default ``MXNET_SERVE_BUCKETS`` /
        ``MXNET_SERVE_PROMPT_BUCKETS``.
    kv_block / kv_max : int, optional
        Cache-length quantum and cap; default ``MXNET_SERVE_KV_BLOCK``
        / ``MXNET_SERVE_KV_MAX``.
    compute_dtype : str, optional
        None (fp32, the parity baseline), ``'bfloat16'`` (weights cast
        once at load, decode-mode compute follows them, logits return
        fp32) or ``'int8'`` (matmul weights quantized once at load into
        ``(codes, scales)`` pairs — ``transformer_lm.
        quantize_lm_params`` — dequantized in-program through the fused
        dequant-matmul door; ~4x less resident weight memory).
    kv_dtype : str, optional
        KV-cache element dtype: ``'float32'`` or ``'bfloat16'``
        (halves cache bytes per slot, so the same ``MXNET_SERVE_KV_
        MAX`` memory budget holds twice the concurrent sequences);
        default ``MXNET_SERVE_KV_DTYPE``.  Attention over the cache
        accumulates fp32 in the kernel AND the dense twin regardless.
    sample : str, optional
        ``'graph'`` (default via ``MXNET_SERVE_SAMPLE``) compiles
        sampling INTO the decode programs (``decode_sample`` kind:
        per-slot PRNG keys ride as a donated argument, the host fetch
        shrinks from (slots, vocab) logits to (slots,) tokens);
        ``'host'`` keeps the logits-returning decode programs — the
        escape hatch, byte-identical token streams (shared
        :func:`sample_tokens`).
    paged : bool, optional
        Paged KV plane (default ``MXNET_SERVE_PAGED``): cache memory
        becomes a global pool of ``kv_block``-token blocks addressed
        through per-slot block tables; the decode engine runs unified
        ``paged_step`` programs (chunked prefill + decode) with
        copy-on-write prefix sharing instead of the prefill/decode
        pair over per-slot cache rectangles.  ``paged=False`` is the
        contiguous escape hatch (bit-identical token streams, pinned
        by tests/test_paged_decode.py).
    prefill_chunk : int, optional
        Chunked-prefill quantum of the paged plane (default
        ``MXNET_SERVE_PREFILL_CHUNK``; clamped to ``kv_max``).
    pool_blocks : int, optional
        Physical block count of the paged pool, including the
        reserved trash block 0 (default ``MXNET_SERVE_KV_POOL_
        BLOCKS``; 0 = auto-size for the largest batch bucket at full
        ``kv_max`` depth).
    max_programs : int, optional
        LRU bound; default is sized to hold every warmable program
        (never smaller than ``MXNET_SERVE_PROGRAM_CACHE``).
    device : jax.Device, optional
        Pin params (and hence programs + cache) to this device.
    """

    def __init__(self, params, spec, name="lm", batch_buckets=None,
                 prompt_buckets=None, kv_block=None, kv_max=None,
                 compute_dtype=None, kv_dtype=None, sample=None,
                 paged=None, prefill_chunk=None, pool_blocks=None,
                 max_programs=None, device=None):
        from ..models.transformer_lm import lm_spec
        self._spec = lm_spec(**dict(spec))  # validates + canonicalizes
        self.name = name
        self._device = device
        self._compute = None
        if compute_dtype:
            c = str(compute_dtype).lower()
            if c in ("float32", "fp32"):
                c = None
            elif c not in ("bfloat16", "int8"):
                raise MXNetError(
                    "generative compute_dtype must be None/'float32'/"
                    "'bfloat16'/'int8', got %r" % compute_dtype)
            self._compute = c
        kv = str(kv_dtype if kv_dtype is not None
                 else get_env("MXNET_SERVE_KV_DTYPE") or "float32")
        if kv not in ("float32", "bfloat16", "int8"):
            raise MXNetError("kv_dtype must be 'float32', 'bfloat16' or "
                             "'int8', got %r" % kv)
        # int8 KV: pool blocks hold int8 codes with per-(layer, head,
        # block) fp32 absmax scales riding as a parallel donated scale
        # pool — a paged-plane feature (the contiguous plane has no
        # block granularity to hang the scales on)
        self.kv_int8 = kv == "int8"
        self.kv_dtype = jnp.dtype(kv)
        sm = str(sample if sample is not None
                 else get_env("MXNET_SERVE_SAMPLE") or "graph").lower()
        if sm not in ("graph", "host"):
            raise MXNetError("MXNET_SERVE_SAMPLE must be 'graph' or "
                             "'host', got %r" % sm)
        self.sample_mode = sm
        self._batch_edges = bucket_edges(batch_buckets)
        self._prompt_edges = bucket_edges(
            prompt_buckets, env_var="MXNET_SERVE_PROMPT_BUCKETS")
        self.kv_block = int(kv_block if kv_block is not None
                            else get_env("MXNET_SERVE_KV_BLOCK"))
        self.kv_max = int(kv_max if kv_max is not None
                          else get_env("MXNET_SERVE_KV_MAX"))
        if self.kv_block < 1 or self.kv_max < self.kv_block:
            raise MXNetError("need 1 <= kv_block <= kv_max, got %d/%d"
                             % (self.kv_block, self.kv_max))
        if self._prompt_edges[-1] > self.kv_max:
            raise MXNetError(
                "largest prompt bucket (%d) exceeds MXNET_SERVE_KV_MAX "
                "(%d)" % (self._prompt_edges[-1], self.kv_max))

        # paged KV plane: cache memory as a global pool of kv_block-
        # token blocks addressed through per-slot block tables
        # (docs/architecture/decode_engine.md).  MXNET_SERVE_PAGED=0
        # (or paged=False) keeps the contiguous per-slot plane.
        self.paged = bool(int(get_env("MXNET_SERVE_PAGED"))
                          if paged is None else paged)
        if self.kv_int8 and not self.paged:
            raise MXNetError(
                "kv_dtype='int8' needs the paged KV plane (the scales "
                "are per pool block); set MXNET_SERVE_PAGED=1 or use "
                "'float32'/'bfloat16' on the contiguous plane")
        chunk = int(prefill_chunk if prefill_chunk is not None
                    else get_env("MXNET_SERVE_PREFILL_CHUNK"))
        if chunk < 1:
            raise MXNetError("prefill_chunk must be >= 1, got %d"
                             % chunk)
        self.prefill_chunk = min(chunk, self.kv_max)
        nb = int(pool_blocks if pool_blocks is not None
                 else get_env("MXNET_SERVE_KV_POOL_BLOCKS"))
        if nb <= 0:
            # auto: the largest batch bucket at full kv_max depth,
            # plus the reserved trash block 0
            nb = self._batch_edges[-1] * self.table_width() + 1
        if self.paged and nb < self.table_width() + 1:
            raise MXNetError(
                "paged KV pool of %d blocks cannot hold one full-"
                "depth sequence (%d blocks + the reserved trash "
                "block); raise MXNET_SERVE_KV_POOL_BLOCKS"
                % (nb, self.table_width()))
        self.pool_blocks = nb
        self._copy_fn = None   # lazily jitted COW block copy
        self._copy_fn8 = None  # its int8 codes+scales twin

        missing = [k for k in self._required_params() if k not in params]
        if missing:
            raise MXNetError("generative model %r is missing params %s"
                             % (name, missing))

        self._params = self._load_params(params)
        self._version = 1

        # one warm sweep must fit the LRU or AOT is a lie (the forward
        # store logs the same hazard; here we just size for it).  The
        # paged plane's warm set is per (batch bucket, step length):
        # one decode (lq=1) and one prefill-chunk program per bucket.
        if self.paged:
            n_warm = (len(self._batch_edges) *
                      len({1, self.prefill_chunk}))
        else:
            n_warm = (len(self._batch_edges) * len(self._prompt_edges) +
                      len(self._batch_edges) *
                      len({self.kv_bucket(p) for p in self._prompt_edges}))
        if max_programs is None:
            max_programs = max(int(get_env("MXNET_SERVE_PROGRAM_CACHE")),
                               2 * n_warm)
        self.max_programs = max(1, int(max_programs))
        if self.max_programs < n_warm:
            log.warning(
                "generative model %r: program cache (%d) is smaller "
                "than the warm set (%d); warmed programs will be "
                "evicted and recompile inside served requests",
                name, self.max_programs, n_warm)
        self._programs = OrderedDict()
        self._lock = make_lock("serving.gen_program_store")
        self._stats = {"hits": 0, "compiles": 0, "evictions": 0,
                       "compile_ms_total": 0.0}
        # live decode state (attached by the GenerationEngine): the
        # cache lives here, beside the params — registry-owned serving
        # state, introspectable via stats()
        self.cache_state = None

    def _load_params(self, params):
        """The trained weight dict through the serving dtype policy
        (fp32 pass-through / bf16 cast / int8 matmul-weight
        quantization) and device pinning; shared by load and
        :meth:`swap_params` so both produce identical trees."""
        device = self._device

        def load(v):
            a = _as_device_array(v)
            if self._compute == "bfloat16" and \
                    jnp.issubdtype(a.dtype, jnp.floating) and \
                    a.dtype != jnp.bfloat16:
                a = a.astype(jnp.bfloat16)
            if device is not None:
                a = jax.device_put(a, device)
            return a

        if self._compute == "int8":
            from ..models.transformer_lm import quantize_lm_params
            host = {k: np.asarray(_as_device_array(v), np.float32)
                    if jnp.issubdtype(_as_device_array(v).dtype,
                                      jnp.floating) else v
                    for k, v in params.items()}
            out = {}
            for k, v in quantize_lm_params(host, self._spec).items():
                if isinstance(v, QuantizedWeight):
                    c, s = jnp.asarray(v.codes), jnp.asarray(v.scales)
                    if device is not None:
                        c = jax.device_put(c, device)
                        s = jax.device_put(s, device)
                    out[k] = QuantizedWeight(c, s)
                else:
                    out[k] = load(v)
            return out
        return {k: load(v) for k, v in params.items()}

    # -- hot weight swap -----------------------------------------------
    def swap_params(self, params):
        """Atomically republish the decode plane's weight arguments
        (same contract as :meth:`ProgramStore.swap_params`: identical
        names/shapes/dtypes, no recompile, one reference assignment).
        Each program DISPATCH binds one version — a prefill or a decode
        step is never torn — but a multi-step generation that straddles
        the swap continues on the NEW weights from its next step (its
        KV cache holds old-version context); latency-sensitive
        deployments that need whole-generation pinning should drain
        before swapping.  Returns the new version."""
        missing = [k for k in self._required_params() if k not in params]
        if missing:
            raise MXNetError("swap_params for %r is missing %s"
                             % (self.name, sorted(missing)))
        new_params = self._load_params(params)
        old_leaves = jax.tree_util.tree_leaves(
            {k: self._params[k] for k in sorted(self._params)})
        new_leaves = jax.tree_util.tree_leaves(
            {k: new_params[k] for k in sorted(self._params)
             if k in new_params})
        if sorted(new_params) != sorted(self._params) or any(
                a.shape != b.shape or a.dtype != b.dtype
                for a, b in zip(new_leaves, old_leaves)):
            raise MXNetError(
                "swap_params for %r: the new weight set does not match "
                "the compiled programs' signature (the decode programs "
                "are not recompiled on swap)" % self.name)
        with self._lock:
            self._params = new_params
            self._version += 1
        return self._version

    @property
    def version(self):
        return self._version

    def param_snapshot(self):
        """Opaque live-weight handle for :meth:`restore_params` (same
        contract as ``ProgramStore.param_snapshot``)."""
        with self._lock:
            return dict(self._params)

    def restore_params(self, snap):
        """Republish a :meth:`param_snapshot` (rolling-swap abort
        path); bumps the version."""
        with self._lock:
            self._params = dict(snap)
            self._version += 1
        return self._version

    def _required_params(self):
        names = ["embed_weight", "final_ln_gamma", "final_ln_beta",
                 "pred_weight", "pred_bias"]
        for i in range(self._spec["num_layers"]):
            names += ["blk%d_%s" % (i, k) for k in
                      ("ln1_gamma", "q_weight", "k_weight", "v_weight",
                       "proj_weight", "ln2_gamma", "ffn1_weight",
                       "ffn1_bias", "ffn2_weight", "ffn2_bias")]
        return names

    # -- geometry ------------------------------------------------------
    @property
    def spec(self):
        return dict(self._spec)

    @property
    def batch_edges(self):
        return self._batch_edges

    @property
    def prompt_edges(self):
        return self._prompt_edges

    def max_slots(self):
        return self._batch_edges[-1]

    def batch_bucket(self, n):
        b = bucket_for(n, self._batch_edges)
        if b is None:
            raise MXNetError("batch of %d sequences exceeds the largest "
                             "serving bucket (%d)"
                             % (n, self._batch_edges[-1]))
        return b

    def prompt_bucket(self, p):
        b = bucket_for(p, self._prompt_edges)
        if b is None:
            raise MXNetError(
                "prompt of %d tokens exceeds the largest prompt bucket "
                "(%d); raise MXNET_SERVE_PROMPT_BUCKETS or truncate"
                % (p, self._prompt_edges[-1]))
        return b

    def kv_bucket(self, length):
        """Cache length quantized UP to the kv-block quantum."""
        length = max(1, int(length))
        c = -(-length // self.kv_block) * self.kv_block
        if c > self.kv_max:
            raise MXNetError(
                "sequence needs a %d-token cache, past MXNET_SERVE_KV_"
                "MAX (%d)" % (c, self.kv_max))
        return c

    def table_width(self):
        """Block-table width of the paged plane: logical blocks needed
        to address a full kv_max-token sequence."""
        return -(-self.kv_max // self.kv_block)

    def validate_request(self, prompt_len, max_tokens):
        """Reject at submit anything whose cache could outgrow kv_max
        mid-flight.  On the contiguous plane the prompt must also fit
        a prompt bucket; the paged plane chunks prompts, so only the
        kv_max total and the pool's physical capacity bound it."""
        need = int(prompt_len) + max(1, int(max_tokens))
        if need > self.kv_max:
            raise MXNetError(
                "prompt_len %d + max_tokens %d exceeds MXNET_SERVE_KV_"
                "MAX (%d)" % (prompt_len, max_tokens, self.kv_max))
        if self.paged:
            blocks = -(-need // self.kv_block)
            if blocks > self.pool_blocks - 1:
                raise MXNetError(
                    "request needs %d KV blocks, past the paged pool's "
                    "%d usable blocks (MXNET_SERVE_KV_POOL_BLOCKS)"
                    % (blocks, self.pool_blocks - 1))
        else:
            self.prompt_bucket(int(prompt_len))

    def new_cache(self, batch, cache_len):
        from ..models.transformer_lm import init_cache
        k, v = init_cache(self._spec, batch, cache_len,
                          dtype=self.kv_dtype)
        if self._device is not None:
            k = jax.device_put(k, self._device)
            v = jax.device_put(v, self._device)
        return k, v

    def new_pool(self):
        """Zeroed paged KV pool pair, ``(num_layers, num_heads,
        pool_blocks * kv_block, head_dim)`` each — block 0 is the
        reserved trash block zero table entries point at."""
        from ..models.transformer_lm import init_pool
        k, v = init_pool(self._spec, self.pool_blocks, self.kv_block,
                         dtype=self.kv_dtype)
        if self._device is not None:
            k = jax.device_put(k, self._device)
            v = jax.device_put(v, self._device)
        return k, v

    def new_scale_pool(self):
        """Per-(layer, head, physical block) fp32 absmax scale pools
        for the int8 paged plane — a ``(num_layers, num_heads,
        pool_blocks)`` pair of ones riding beside :meth:`new_pool`'s
        int8 code pools as donated program state."""
        from ..models.transformer_lm import init_scale_pool
        sk, sv = init_scale_pool(self._spec, self.pool_blocks)
        if self._device is not None:
            sk = jax.device_put(sk, self._device)
            sv = jax.device_put(sv, self._device)
        return sk, sv

    def copy_block(self, pool_k, pool_v, src, dst, scales=None):
        """Copy-on-write fork: duplicate physical block ``src``'s rows
        into block ``dst`` in both pools (one jitted program, pools
        donated off-CPU — callers rebind to the outputs).  With
        ``scales`` (the int8 plane's ``(scale_k, scale_v)`` pools) the
        per-block scales fork WITH the codes — a block is only
        decodable as codes+scale together — and the return grows to
        ``(pool_k, pool_v, scale_k, scale_v)``."""
        bs = self.kv_block
        if scales is not None:
            fn = getattr(self, "_copy_fn8", None)
            if fn is None:
                def f8(pk, pv, sk, sv, s, d):
                    bk = jax.lax.dynamic_slice_in_dim(pk, s * bs, bs, 2)
                    bv = jax.lax.dynamic_slice_in_dim(pv, s * bs, bs, 2)
                    pk = jax.lax.dynamic_update_slice_in_dim(pk, bk,
                                                             d * bs, 2)
                    pv = jax.lax.dynamic_update_slice_in_dim(pv, bv,
                                                             d * bs, 2)
                    ssk = jax.lax.dynamic_slice_in_dim(sk, s, 1, 2)
                    ssv = jax.lax.dynamic_slice_in_dim(sv, s, 1, 2)
                    sk = jax.lax.dynamic_update_slice_in_dim(sk, ssk,
                                                             d, 2)
                    sv = jax.lax.dynamic_update_slice_in_dim(sv, ssv,
                                                             d, 2)
                    return pk, pv, sk, sv

                fn = self._copy_fn8 = jax.jit(
                    f8, donate_argnums=cache_donate_argnums((0, 1, 2,
                                                             3)))
            return fn(pool_k, pool_v, scales[0], scales[1],
                      np.int32(src), np.int32(dst))
        fn = self._copy_fn
        if fn is None:
            def f(pk, pv, s, d):
                bk = jax.lax.dynamic_slice_in_dim(pk, s * bs, bs, 2)
                bv = jax.lax.dynamic_slice_in_dim(pv, s * bs, bs, 2)
                pk = jax.lax.dynamic_update_slice_in_dim(pk, bk,
                                                         d * bs, 2)
                pv = jax.lax.dynamic_update_slice_in_dim(pv, bv,
                                                         d * bs, 2)
                return pk, pv

            fn = self._copy_fn = jax.jit(
                f, donate_argnums=cache_donate_argnums((0, 1)))
        return fn(pool_k, pool_v, np.int32(src), np.int32(dst))

    # -- compilation ---------------------------------------------------
    def _sds(self, shape, dtype):
        sh = (jax.sharding.SingleDeviceSharding(self._device)
              if self._device is not None else None)
        return jax.ShapeDtypeStruct(shape, dtype, sharding=sh)

    def _param_spec(self):
        # tree_map descends QuantizedWeight pairs to their code/scale
        # leaves (registered pytree), so int8 params spec like arrays
        return jax.tree_util.tree_map(
            lambda a: self._sds(a.shape, a.dtype), self._params)

    def _cache_spec(self, batch, cache_len):
        s = self._spec
        dh = s["num_hidden"] // s["num_heads"]
        shape = (s["num_layers"], batch, s["num_heads"],
                 int(cache_len), dh)
        return self._sds(shape, self.kv_dtype)

    def _pool_spec(self):
        s = self._spec
        dh = s["num_hidden"] // s["num_heads"]
        shape = (s["num_layers"], s["num_heads"],
                 self.pool_blocks * self.kv_block, dh)
        return self._sds(shape, self.kv_dtype)

    def _scale_spec(self):
        s = self._spec
        return self._sds((s["num_layers"], s["num_heads"],
                          self.pool_blocks), jnp.float32)

    def _key(self, kind, bb, lb):
        # (kind, batch bucket, length bucket) + the serving dtypes +
        # the dispatch fingerprint (prefill/decode trace through
        # sdp_attention, the rowwise norm kernels and the dequant-
        # matmul door — an MXNET_PALLAS flip must recompile, not serve
        # the stale lowering; the dtypes are per-store constants, in
        # the key as insurance)
        return ("gen", self.name, kind, int(bb), int(lb),
                self._compute, str(self.kv_dtype),
                _pallas_dispatch.fingerprint())

    def _compile(self, kind, bb, lb):
        from ..models.transformer_lm import (decode_apply,
                                             paged_step_apply,
                                             prefill_apply)
        tic = time.perf_counter()
        spec = self._spec
        kv = self.kv_dtype
        if kind in ("paged_step", "paged_step_sample",
                    "paged_step_sample_p", "paged_verify"):
            # ONE unified step program for the paged plane: lb is the
            # query length lq (1 = a decode step; prefill_chunk = one
            # prompt chunk; spec_k+1 = a speculative verify).  Scatter-
            # then-attend over the global pool through (bb, table_width)
            # block tables; rows not participating in a dispatch ride
            # with all-zero tables (writes land in the reserved trash
            # block 0) and their outputs are discarded host-side.  On
            # the int8 plane every kind gains the two donated scale
            # pools right after the code pools, in arguments AND
            # returns.
            bs = self.kv_block
            tb = self.table_width()
            int8 = self.kv_int8
            pools = ((self._pool_spec(), self._pool_spec(),
                      self._scale_spec(), self._scale_spec())
                     if int8 else
                     (self._pool_spec(), self._pool_spec()))
            npool = len(pools)
            base = (self._param_spec(),) + pools + (
                self._sds((bb, tb), jnp.int32),
                self._sds((bb, int(lb)), jnp.int32),
                self._sds((bb,), jnp.int32),
                self._sds((bb,), jnp.int32))
            samp = (self._sds((bb, 2), jnp.uint32),
                    self._sds((bb,), jnp.float32),
                    self._sds((bb,), jnp.int32),
                    self._sds((bb,), jnp.bool_))
            pool_donate = tuple(range(1, 1 + npool))

            def step(params, pls, tables, tokens, positions, valid,
                     all_logits=False):
                # paged_step_apply with the pool tuple threaded through
                # the fp/int8 layouts uniformly: returns (logits,
                # new_pool_tuple)
                if int8:
                    out = paged_step_apply(
                        params, pls[0], pls[1], tables, tokens,
                        positions, valid, spec, bs,
                        scales=(pls[2], pls[3]), all_logits=all_logits)
                else:
                    out = paged_step_apply(
                        params, pls[0], pls[1], tables, tokens,
                        positions, valid, spec, bs,
                        all_logits=all_logits)
                return out[0], tuple(out[1:])

            if kind in ("paged_step_sample", "paged_step_sample_p"):
                # in-graph sampling with a per-row enable mask: a
                # chunk dispatch samples ONLY the rows finishing their
                # prompt this tick (do_sample), everyone else's PRNG
                # chain must not advance.  The _p variant additionally
                # emits the proposal distribution q — the draft model's
                # step in speculative decoding.
                with_q = kind == "paged_step_sample_p"

                def fn(params, *rest):
                    pls = rest[:npool]
                    (tables, tokens, positions, valid, keys, temps,
                     top_ks, do_sample) = rest[npool:]
                    logits, new_pools = step(params, pls, tables,
                                             tokens, positions, valid)
                    if with_q:
                        toks, carry, q = sample_tokens_p(
                            logits, keys, temps, top_ks)
                    else:
                        toks, carry = sample_tokens(logits, keys,
                                                    temps, top_ks)
                    new_keys = jnp.where(do_sample[:, None], carry,
                                         keys)
                    head = (toks, q) if with_q else (toks,)
                    return head + new_pools + (new_keys,)

                args = base + samp
                compiled = jax.jit(
                    fn, donate_argnums=cache_donate_argnums(
                        pool_donate + (len(base),))) \
                    .lower(*args).compile()
            elif kind == "paged_verify":
                # speculative verify: all lb=K+1 positions' logits stay
                # in-graph, the rejection rule runs beside them
                # (spec_verify), and the host fetch is two small
                # integer vectors — never logits.  tokens[:, 0] is the
                # slot's pending next token, tokens[:, 1:] the K draft
                # proposals; prop_q is the draft's (bb, K, vocab)
                # proposal distribution from paged_step_sample_p.
                K = int(lb) - 1

                def fn(params, *rest):
                    pls = rest[:npool]
                    (tables, tokens, positions, valid, prop_q, keys,
                     temps, top_ks, do_sample) = rest[npool:]
                    logits_all, new_pools = step(params, pls, tables,
                                                 tokens, positions,
                                                 valid, all_logits=True)
                    out, n_emit, carry = spec_verify(
                        logits_all, tokens[:, 1:], prop_q, keys,
                        temps, top_ks, valid)
                    new_keys = jnp.where(do_sample[:, None], carry,
                                         keys)
                    return (out, n_emit) + new_pools + (new_keys,)

                args = base + (self._sds((bb, K, spec["vocab_size"]),
                                         jnp.float32),) + samp
                compiled = jax.jit(
                    fn, donate_argnums=cache_donate_argnums(
                        pool_donate + (len(base) + 1,))) \
                    .lower(*args).compile()
            else:   # paged_step (logits out — the host-sampling hatch)
                def fn(params, *rest):
                    pls = rest[:npool]
                    tables, tokens, positions, valid = rest[npool:]
                    logits, new_pools = step(params, pls, tables,
                                             tokens, positions, valid)
                    return (logits,) + new_pools

                compiled = jax.jit(
                    fn,
                    donate_argnums=cache_donate_argnums(pool_donate)) \
                    .lower(*base).compile()
            ms = (time.perf_counter() - tic) * 1e3
            return _Program(compiled, (bb, lb), (), ms)
        if kind == "prefill":
            cache_len = self.kv_bucket(lb)

            def fn(params, tokens, lengths):
                logits, ck, cv = prefill_apply(params, tokens, lengths,
                                               cache_len, spec,
                                               cache_dtype=kv)
                first = logits[jnp.arange(bb), (lengths - 1)
                               .astype(jnp.int32)]
                return first, ck, cv

            args = (self._param_spec(),
                    self._sds((bb, lb), jnp.int32),
                    self._sds((bb,), jnp.int32))
            compiled = jax.jit(fn).lower(*args).compile()
        elif kind == "decode_sample":
            # in-graph sampling: the decode step emits TOKENS, not
            # logits — per-slot PRNG keys ride beside the caches and
            # are donated with them (split in-graph each step)

            def fn(params, cache_k, cache_v, tokens, lengths, keys,
                   temps, top_ks):
                logits, ck, cv = decode_apply(params, cache_k, cache_v,
                                              tokens, lengths, spec)
                toks, new_keys = sample_tokens(logits, keys, temps,
                                               top_ks)
                return toks, ck, cv, new_keys

            args = (self._param_spec(),
                    self._cache_spec(bb, lb), self._cache_spec(bb, lb),
                    self._sds((bb,), jnp.int32),
                    self._sds((bb,), jnp.int32),
                    self._sds((bb, 2), jnp.uint32),
                    self._sds((bb,), jnp.float32),
                    self._sds((bb,), jnp.int32))
            compiled = jax.jit(
                fn, donate_argnums=cache_donate_argnums((1, 2, 5))) \
                .lower(*args).compile()
        else:  # decode (logits out — the MXNET_SERVE_SAMPLE=host hatch)

            def fn(params, cache_k, cache_v, tokens, lengths):
                return decode_apply(params, cache_k, cache_v, tokens,
                                    lengths, spec)

            args = (self._param_spec(),
                    self._cache_spec(bb, lb), self._cache_spec(bb, lb),
                    self._sds((bb,), jnp.int32),
                    self._sds((bb,), jnp.int32))
            # the caches are DONATED (off-CPU): the per-step K/V write
            # is an in-place dynamic_update_slice on the one resident
            # copy — callers MUST rebind their cache references to the
            # outputs
            compiled = jax.jit(
                fn, donate_argnums=cache_donate_argnums((1, 2))) \
                .lower(*args).compile()
        ms = (time.perf_counter() - tic) * 1e3
        return _Program(compiled, (bb, lb), (), ms)

    def _acquire(self, kind, bb, lb):
        key = self._key(kind, bb, lb)
        with self._lock:
            prog = self._programs.get(key)
            if prog is not None:
                self._programs.move_to_end(key)
                self._stats["hits"] += 1
                _cache_event("hits").inc()
                return prog
        prog = self._compile(kind, bb, lb)
        with self._lock:
            raced = self._programs.get(key)
            if raced is not None:
                self._stats["hits"] += 1
                _cache_event("hits").inc()
                return raced
            self._stats["compiles"] += 1
            self._stats["compile_ms_total"] += prog.compile_ms
            _cache_event("compiles").inc()
            while len(self._programs) >= self.max_programs:
                self._programs.popitem(last=False)
                self._stats["evictions"] += 1
                _cache_event("evictions").inc()
            self._programs[key] = prog
            return prog

    def warmup(self, execute=True, kv_depth=None):
        """Compile — and by default execute once on zeros — every
        (batch, prompt) prefill program and every (batch, cache-bucket)
        decode program reachable from the prompt buckets, ahead of
        traffic.  ``kv_depth`` additionally warms every cache bucket up
        to that length (a sequence *growing* past its prompt's quantum
        otherwise pays that decode compile at its first step into the
        new bucket — serving processes that know their generation caps
        should pass ``kv_depth=prompt_max + max_tokens_max``).  Returns
        {(kind, bb, lb): compile_ms}."""
        out = {}
        if self.paged:
            # the paged plane's whole program space: one unified step
            # program per (batch bucket, step length) — lq=1 decode
            # steps and lq=prefill_chunk prompt chunks.  kv_depth is
            # moot: the table width is a store constant, so cache
            # depth never changes the program.  Warmup executes on a
            # throwaway zero pool with all-zero tables (every write
            # lands in the trash block).
            pkind = ("paged_step_sample" if self.sample_mode == "graph"
                     else "paged_step")
            for bb in self._batch_edges:
                for lq in sorted({1, self.prefill_chunk}):
                    prog = self._acquire(pkind, bb, lq)
                    out[(pkind, bb, lq)] = prog.compile_ms
                    if execute:
                        self._exec_paged_zeros(pkind, prog, bb, lq)
            return out
        cache_buckets = {self.kv_bucket(p) for p in self._prompt_edges}
        if kv_depth is not None:
            top = self.kv_bucket(kv_depth)
            cache_buckets.update(
                range(self.kv_block, top + 1, self.kv_block))
        # the decode kind the engine will dispatch: tokens-out
        # (in-graph sampling) or logits-out (the host hatch)
        dkind = ("decode_sample" if self.sample_mode == "graph"
                 else "decode")
        for bb in self._batch_edges:
            for pb in self._prompt_edges:
                prog = self._acquire("prefill", bb, pb)
                out[("prefill", bb, pb)] = prog.compile_ms
                if execute:
                    toks = np.zeros((bb, pb), np.int32)
                    lens = np.ones((bb,), np.int32)
                    jax.block_until_ready(
                        prog.fn(self._params, toks, lens))
            for cb in sorted(cache_buckets):
                prog = self._acquire(dkind, bb, cb)
                out[(dkind, bb, cb)] = prog.compile_ms
                if execute:
                    ck, cv = self.new_cache(bb, cb)
                    toks = np.zeros((bb,), np.int32)
                    lens = np.zeros((bb,), np.int32)
                    if dkind == "decode_sample":
                        jax.block_until_ready(prog.fn(
                            self._params, ck, cv, toks, lens,
                            np.zeros((bb, 2), np.uint32),
                            np.zeros((bb,), np.float32),
                            np.zeros((bb,), np.int32)))
                    else:
                        jax.block_until_ready(
                            prog.fn(self._params, ck, cv, toks, lens))
        return out

    def _exec_paged_zeros(self, kind, prog, bb, lq):
        """Execute one paged program once on a throwaway zero pool with
        all-zero tables (every write lands in the trash block): the
        one-time XLA executable setup must not land inside a served
        request."""
        pools = self.new_pool()
        if self.kv_int8:
            pools = pools + self.new_scale_pool()
        tbls = np.zeros((bb, self.table_width()), np.int32)
        toks = np.zeros((bb, lq), np.int32)
        pos = np.zeros((bb,), np.int32)
        val = np.ones((bb,), np.int32)
        samp = (np.zeros((bb, 2), np.uint32),
                np.zeros((bb,), np.float32),
                np.zeros((bb,), np.int32),
                np.zeros((bb,), np.bool_))
        if kind == "paged_verify":
            q = np.zeros((bb, lq - 1, self._spec["vocab_size"]),
                         np.float32)
            args = (self._params,) + pools + (tbls, toks, pos, val,
                                              q) + samp
        elif kind in ("paged_step_sample", "paged_step_sample_p"):
            args = (self._params,) + pools + (tbls, toks, pos,
                                              val) + samp
        else:
            args = (self._params,) + pools + (tbls, toks, pos, val)
        jax.block_until_ready(prog.fn(*args))

    def warm_spec_programs(self, spec_k, draft=False, execute=True):
        """Warm the speculative-decoding program kinds ahead of
        traffic: the TARGET's verify programs (lq = spec_k + 1), or —
        ``draft=True`` — the DRAFT's proposal programs (lq=1
        ``paged_step_sample_p``) plus its logits-discarded
        prefill-mirror chunks (lq = prefill_chunk ``paged_step``).
        ``registry.add_draft_model`` warms both sides, so attaching a
        draft never compiles inside a served request.  Returns
        {(kind, bb, lq): compile_ms}."""
        if not self.paged:
            raise MXNetError(
                "speculative decoding needs the paged plane (store %r "
                "has paged=False)" % self.name)
        kinds = ([("paged_step_sample_p", 1),
                  ("paged_step", self.prefill_chunk)] if draft
                 else [("paged_verify", int(spec_k) + 1)])
        out = {}
        for bb in self._batch_edges:
            for kind, lq in kinds:
                prog = self._acquire(kind, bb, lq)
                out[(kind, bb, lq)] = prog.compile_ms
                if execute:
                    self._exec_paged_zeros(kind, prog, bb, lq)
        return out

    # -- execution -----------------------------------------------------
    @hot_path
    def run_prefill(self, tokens, lengths):
        """Dispatch one padded prompt batch.  ``tokens`` (bb, pb) int32
        and ``lengths`` (bb,) int32 must already be bucket-shaped
        (``pad_prompts``).  Returns device-resident
        ``(first_logits (bb, vocab), k_cache, v_cache)`` — enqueue-only,
        fetch on the caller's side."""
        bb, pb = tokens.shape
        prog = self._acquire("prefill", bb, pb)
        return prog.fn(self._params, tokens, lengths)

    @hot_path
    def run_decode(self, cache_k, cache_v, tokens, lengths):
        """Dispatch one logits-out decode step over a bucket-shaped
        cache (the ``MXNET_SERVE_SAMPLE=host`` hatch and the test
        references).  BOTH cache arguments are consumed (donated) —
        callers must rebind their references to the returned caches."""
        bb = int(tokens.shape[0])
        cb = int(cache_k.shape[3])
        prog = self._acquire("decode", bb, cb)
        return prog.fn(self._params, cache_k, cache_v, tokens, lengths)

    @hot_path
    def run_decode_sample(self, cache_k, cache_v, tokens, lengths,
                          keys, temps, top_ks):
        """Dispatch one decode step with IN-GRAPH sampling: returns
        ``(tokens (bb,) int32, new_k, new_v, new_keys)``.  The caches
        AND the per-slot PRNG key state are consumed (donated) —
        callers rebind all three; the only host-sized fetch left per
        step is the token vector."""
        bb = int(tokens.shape[0])
        cb = int(cache_k.shape[3])
        prog = self._acquire("decode_sample", bb, cb)
        return prog.fn(self._params, cache_k, cache_v, tokens, lengths,
                       keys, temps, top_ks)

    def _pool_args(self, pool_k, pool_v, scales):
        """The pool-argument tuple of one paged dispatch: the int8
        plane threads its donated scale pools right after the code
        pools (and gets them back in the same slots of the return)."""
        if self.kv_int8:
            if scales is None:
                raise MXNetError(
                    "int8 paged store %r needs its (scale_k, scale_v) "
                    "pools on every dispatch" % self.name)
            return (pool_k, pool_v, scales[0], scales[1])
        return (pool_k, pool_v)

    @hot_path
    def run_paged_step(self, pool_k, pool_v, tables, tokens,
                       positions, valid, scales=None):
        """Dispatch one logits-out paged step (the host-sampling
        hatch and the draft's prefill mirror): ``tokens`` (bb, lq)
        int32 — lq=1 is a decode step, lq=prefill_chunk a prompt chunk.
        Returns ``(logits (bb, vocab) at each row's last valid
        position, pool_k, pool_v)`` — int8 stores take and return the
        scale pools too, ``(logits, pool_k, pool_v, scale_k,
        scale_v)``.  The pools are consumed (donated) — callers
        rebind."""
        bb, lq = tokens.shape
        prog = self._acquire("paged_step", int(bb), int(lq))
        return prog.fn(self._params,
                       *(self._pool_args(pool_k, pool_v, scales) +
                         (tables, tokens, positions, valid)))

    @hot_path
    def run_paged_step_sample(self, pool_k, pool_v, tables, tokens,
                              positions, valid, keys, temps, top_ks,
                              do_sample, scales=None):
        """Dispatch one paged step with IN-GRAPH sampling: returns
        ``(tokens (bb,) int32, pool_k, pool_v, new_keys)`` (int8
        stores: ``(tokens, pool_k, pool_v, scale_k, scale_v,
        new_keys)``).  Rows with ``do_sample`` False keep their PRNG
        keys (their sampled token is garbage the caller discards);
        pools and keys are consumed (donated) — callers rebind."""
        bb, lq = tokens.shape
        prog = self._acquire("paged_step_sample", int(bb), int(lq))
        return prog.fn(self._params,
                       *(self._pool_args(pool_k, pool_v, scales) +
                         (tables, tokens, positions, valid, keys,
                          temps, top_ks, do_sample)))

    @hot_path
    def run_paged_step_sample_p(self, pool_k, pool_v, tables, tokens,
                                positions, valid, keys, temps, top_ks,
                                do_sample, scales=None):
        """The DRAFT model's proposal step: one lq=1 paged step with
        in-graph sampling that also returns the proposal distribution.
        Returns ``(tokens (bb,), q (bb, vocab), pool_k, pool_v,
        new_keys)`` (int8: scale pools before new_keys).  ``q`` should
        stay device-resident — the verify program consumes it directly,
        the host never fetches a distribution."""
        bb, lq = tokens.shape
        prog = self._acquire("paged_step_sample_p", int(bb), int(lq))
        return prog.fn(self._params,
                       *(self._pool_args(pool_k, pool_v, scales) +
                         (tables, tokens, positions, valid, keys,
                          temps, top_ks, do_sample)))

    @hot_path
    def run_paged_verify(self, pool_k, pool_v, tables, tokens,
                         positions, valid, prop_q, keys, temps,
                         top_ks, do_sample, scales=None):
        """The TARGET model's speculative verify: ``tokens`` (bb, K+1)
        holds each slot's pending next token followed by its K draft
        proposals, ``prop_q`` (bb, K, vocab) the draft's proposal
        distributions (device-resident from
        :meth:`run_paged_step_sample_p`), ``valid`` = per-slot window
        + 1.  All K+1 positions run in ONE program; accept/reject and
        the corrected resample happen in-graph (``spec_verify``).
        Returns ``(out_toks (bb, K+1), n_emit (bb,), pool_k, pool_v,
        new_keys)`` (int8: scale pools before new_keys) — row b emits
        ``out_toks[b, :n_emit[b]]``.  Pools and keys are consumed
        (donated) — callers rebind."""
        bb, lq = tokens.shape
        prog = self._acquire("paged_verify", int(bb), int(lq))
        return prog.fn(self._params,
                       *(self._pool_args(pool_k, pool_v, scales) +
                         (tables, tokens, positions, valid, prop_q,
                          keys, temps, top_ks, do_sample)))

    def pad_prompts(self, prompts):
        """Host-side canonicalization: a list of token id sequences ->
        bucket-shaped ``(tokens (bb, pb) int32, lengths (bb,) int32)``.
        Pad rows get length 1 over token 0 (their logits are discarded;
        length >= 1 keeps the first-token gather in bounds)."""
        n = len(prompts)
        if n < 1:
            raise MXNetError("empty prompt batch")
        lens = [len(p) for p in prompts]
        if min(lens) < 1:
            raise MXNetError("empty prompt (0 tokens)")
        bb = self.batch_bucket(n)
        pb = self.prompt_bucket(max(lens))
        toks = np.zeros((bb, pb), np.int32)
        lengths = np.ones((bb,), np.int32)
        for i, p in enumerate(prompts):
            toks[i, :lens[i]] = np.asarray(p, np.int32)
            lengths[i] = lens[i]
        return toks, lengths

    # -- introspection -------------------------------------------------
    def stats(self):
        with self._lock:
            out = dict(self._stats)
            out["size"] = len(self._programs)
            out["max_programs"] = self.max_programs
            out["programs_resident"] = sorted(
                (k[2], k[3], k[4]) for k in self._programs)
        out["generative"] = True
        out["version"] = self._version
        out["batch_buckets"] = list(self._batch_edges)
        out["prompt_buckets"] = list(self._prompt_edges)
        out["kv_block"] = self.kv_block
        out["kv_max"] = self.kv_max
        out["compute_dtype"] = self._compute
        out["kv_dtype"] = str(self.kv_dtype)
        out["sample_mode"] = self.sample_mode
        out["paged"] = self.paged
        if self.paged:
            out["prefill_chunk"] = self.prefill_chunk
            out["pool_blocks"] = self.pool_blocks
            out["table_width"] = self.table_width()
        out["weight_bytes"] = _weight_bytes(self._params)
        state = self.cache_state
        if state is not None:
            out["cache_state"] = state.describe()
        return out

    def reset_stats(self):
        with self._lock:
            for k in ("hits", "compiles", "evictions"):
                self._stats[k] = 0
            self._stats["compile_ms_total"] = 0.0

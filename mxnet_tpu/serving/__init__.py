"""Production serving plane: AOT-compiled inference with continuous batching.

Three layers (docs/architecture/serving.md):

* :mod:`program_store` — per ``(model, shape-bucket, dtype)`` signature the
  inference program is lowered and compiled **ahead of time**
  (``jax.jit(...).lower(...).compile()``) into a bounded LRU keyed like
  ``cached_op.py``'s; arbitrary request sizes are padded up to a small set
  of configured bucket edges and the pad rows sliced back off the outputs.
* :mod:`scheduler` — :class:`ServingEngine`, a continuous-batching request
  scheduler: one engine thread drains a request queue into the largest
  bucket that fits within a per-request latency budget
  (``MXNET_SERVE_MAX_DELAY_MS`` / ``MXNET_SERVE_MAX_BATCH``), with
  per-request futures, timeout/cancellation, and graceful shutdown that
  drains in-flight work.
* :mod:`registry` — :class:`ModelRegistry`, multi-model tenancy: N models
  served from one process, each with its own program store and optional
  serving weight dtype (bf16, or int8 weight-only through the fused
  dequant-matmul door — ``docs/architecture/serving.md``'s dtype
  matrix).

The decode plane (docs/architecture/decode_engine.md) adds
autoregressive generation on the same registry: :mod:`program_store`'s
:class:`GenerativeProgramStore` splits a generative model into AOT
prefill programs (per batch/prompt bucket, filling the KV cache) and
decode-step programs (per batch/cache bucket, one token per sequence,
cache donated), and :mod:`decode_engine`'s :class:`GenerationEngine`
runs continuous-batched generation over them — admitting newly
prefilled sequences into the running decode batch between steps and
retiring finished ones.

:mod:`loadgen` provides the seeded open-loop load generator (deterministic
arrival schedule, ``faultinject``-style) driving the p50/p99 + QPS bench
rows on CPU in CI — and, for the decode plane, the tokens/sec + TTFT +
inter-token-latency generation protocol.

The control plane (docs/architecture/serving.md, control-plane section)
closes the loop over all of it: :mod:`controller`'s :class:`AutoScaler`
grows and shrinks a :class:`ReplicaSet` off the metrics registry's
queue-wait/shed/utilization signals against an SLO target, the replica
set's ``swap_params`` is a zero-downtime rolling weight swap with
abort-and-rollback, admission understands priority tiers and per-tenant
quotas, and :mod:`loadgen`'s ``autoscale_protocol`` /
``rolling_swap_protocol`` / ``chaos_protocol`` prove the behaviors under
seeded shaped load and composed fault schedules.
"""
from .program_store import (GenerativeProgramStore, ProgramStore,
                            bucket_edges, bucket_for, host_sample,
                            sample_tokens)
from .registry import ModelRegistry
from .scheduler import (TIERS, FutureCompleter, ServeClosed,
                        ServeOverloaded, ServeRequest, ServeTimeout,
                        ServingEngine)
from .decode_engine import GenerationEngine, GenerationResult, TokenStream
from .replica_set import (NoLiveReplicas, Replica, ReplicaDied,
                          ReplicaSet)
from .controller import AutoScaler
from .frontdoor import HttpClient, HttpFrontDoor
from .loadgen import (OpenLoopSchedule, autoscale_protocol,
                      chaos_protocol, failover_protocol,
                      frontdoor_protocol, generation_protocol,
                      latency_protocol, rolling_swap_protocol,
                      run_gen_loadgen, run_loadgen, swap_protocol)

__all__ = [
    "ProgramStore", "GenerativeProgramStore", "bucket_edges", "bucket_for",
    "sample_tokens", "host_sample",
    "ModelRegistry",
    "ServingEngine", "ServeRequest", "ServeTimeout", "ServeClosed",
    "ServeOverloaded", "FutureCompleter", "TIERS",
    "GenerationEngine", "GenerationResult", "TokenStream",
    "Replica", "ReplicaSet", "ReplicaDied", "NoLiveReplicas",
    "AutoScaler",
    "HttpFrontDoor", "HttpClient",
    "OpenLoopSchedule", "run_loadgen", "latency_protocol",
    "run_gen_loadgen", "generation_protocol", "frontdoor_protocol",
    "failover_protocol", "swap_protocol", "autoscale_protocol",
    "rolling_swap_protocol", "chaos_protocol",
]

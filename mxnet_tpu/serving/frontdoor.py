"""HTTP front door: the serving plane's network surface.

Everything behind it — the continuous batcher, the replica set, the
decode engine — speaks ``submit(...) -> Future``; this module puts a
thin, dependency-free HTTP skin on that contract (stdlib
``http.server`` only, mirroring the kvstore plane's stdlib transport
choice) so real traffic can reach it:

* ``POST /v1/models/<name>:predict`` — one forward request.  Two wire
  formats, chosen by Content-Type: ``application/json`` (``{"inputs":
  {name: nested-lists}, "timeout_ms": ...}`` -> ``{"outputs": [...],
  "shapes": ..., "dtypes": ..., "version": ...}``) for curl-ability,
  and ``application/x-npz`` (an ``np.savez`` archive of the inputs;
  reply is an npz of ``output_0..output_k``) for bit-exact binary
  transport — the loadgen's HTTP adapter uses npz so the HTTP rows
  measure transport, not float/JSON round-tripping.
* ``POST /v1/models/<name>:generate`` — one generation request (JSON
  only: token ids are small).
* ``GET /healthz`` — liveness of the target (a balancer's probe
  surface: 200 while something can serve, 503 after).
* ``GET /stats`` — the target's ``stats()`` dict (scheduler counters,
  program-store compile stats, weight versions, replica/breaker state).

**Deadline propagation**: ``timeout_ms`` (JSON body) or the
``X-Mxnet-Timeout-Ms`` header rides into the engine's queue-time
deadline, so an expired request sheds server-side exactly like an
in-process one.  **Structured failure mapping** (the fault contract
clients program against):

==========================  ======  =========
exception                   status  retryable
==========================  ======  =========
ServeTimeout                504     yes
ServeOverloaded             429     yes (back off)
ServeClosed                 503     yes (elsewhere)
NoLiveReplicas              503     yes (elsewhere)
ReplicaDied (generation)    503     yes (resubmit regenerates)
other MXNetError            400     no
anything else               500     no
==========================  ======  =========

:class:`HttpClient` is the matching client AND the loadgen transport
adapter: ``submit(...)`` returns a ``concurrent.futures.Future``
resolved by a small worker pool holding persistent connections, with
HTTP failure statuses mapped BACK to the exception classes above — so
``loadgen.run_loadgen`` drives an HTTP target through the same shared
``_drive_schedule`` driver, classifying timeouts/sheds/errors
identically to in-process targets (the ``serving.frontdoor.*`` bench
rows ride this).
"""
from __future__ import annotations

import hmac
import io
import json
import queue
import threading
import time
from concurrent.futures import Future, InvalidStateError
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from .. import metrics as _metrics
from .. import profiler as _profiler
from .. import tracing as _tracing
from ..base import MXNetError, get_env
from .replica_set import NoLiveReplicas, ReplicaDied
from .scheduler import ServeClosed, ServeOverloaded, ServeTimeout

__all__ = ["HttpFrontDoor", "HttpClient"]

# exception class <-> (HTTP status, retryable): the structured failure
# contract, shared by the server's encoder and the client's decoder
_STATUS = (
    (ServeTimeout, 504, True),
    (ServeOverloaded, 429, True),
    (ReplicaDied, 503, True),
    (NoLiveReplicas, 503, True),
    (ServeClosed, 503, True),
)
_KIND_TO_EXC = {cls.__name__: cls for cls, _s, _r in _STATUS}


def _encode_error(exc):
    """(status, json_body) for one serving exception."""
    for cls, status, retryable in _STATUS:
        if isinstance(exc, cls):
            return status, {"error": str(exc), "kind": cls.__name__,
                            "retryable": retryable}
    if isinstance(exc, MXNetError):
        return 400, {"error": str(exc), "kind": "MXNetError",
                     "retryable": False}
    return 500, {"error": "%s: %s" % (type(exc).__name__, exc),
                 "kind": type(exc).__name__, "retryable": False}


def _decode_error(status, body):
    """The client-side inverse: an exception instance from an error
    reply (unknown kinds degrade to MXNetError with the status)."""
    try:
        d = json.loads(body.decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        d = {"error": body[:200].decode("utf-8", "replace"),
             "kind": None}
    cls = _KIND_TO_EXC.get(d.get("kind"), MXNetError)
    return cls("HTTP %d from serving front door: %s"
               % (status, d.get("error")))


class _Handler(BaseHTTPRequestHandler):
    # one request per connection keep-alive: the loadgen clients hold
    # persistent connections
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet: CI drives 100s of reqs
        pass

    @property
    def _door(self):
        return self.server.frontdoor

    # -- plumbing ------------------------------------------------------
    def _reply(self, status, payload, content_type="application/json"):
        if content_type == "application/json":
            body = json.dumps(payload).encode("utf-8")
        else:
            body = payload
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply_error(self, exc):
        status, body = _encode_error(exc)
        self._reply(status, body)

    def _read_body(self):
        n = int(self.headers.get("Content-Length", 0))
        return self.rfile.read(n) if n else b""

    def _timeout_s(self, payload=None):
        """Deadline from the JSON body (timeout_ms) or the
        X-Mxnet-Timeout-Ms header; None = no deadline."""
        ms = None
        if payload is not None and payload.get("timeout_ms") is not None:
            ms = float(payload["timeout_ms"])
        else:
            h = self.headers.get("X-Mxnet-Timeout-Ms")
            if h:
                ms = float(h)
        return None if ms is None else max(0.0, ms) / 1e3

    def _tier_tenant(self, payload=None):
        """Admission metadata from the JSON body (``priority`` /
        ``tenant``) or — the npz transport's only channel — the
        ``X-Mxnet-Priority`` / ``X-Mxnet-Tenant`` headers.  Unknown
        tiers fail in the engine with a structured 400."""
        priority = tenant = None
        if payload is not None:
            priority = payload.get("priority")
            tenant = payload.get("tenant")
        if priority is None:
            priority = self.headers.get("X-Mxnet-Priority") or None
        if tenant is None:
            tenant = self.headers.get("X-Mxnet-Tenant") or None
        return priority, tenant

    def _authorized(self):
        """Bearer-token gate (``MXNET_SERVE_AUTH_TOKEN``).  No token
        configured = open door (in-cluster default).  ``/healthz`` and
        ``/metrics`` stay exempt so balancer probes and scrapers need
        no credential plumbing.  Failures get a structured 401 the
        client maps like every other serving error."""
        tok = self._door.auth_token
        if not tok or self.path in ("/healthz", "/metrics"):
            return True
        h = self.headers.get("Authorization") or ""
        # constant-time compare: the token must not leak via timing
        if h.startswith("Bearer ") and hmac.compare_digest(
                h[len("Bearer "):], tok):
            return True
        self._reply(401, {"error": "missing or invalid bearer token "
                                   "(Authorization: Bearer <token>)",
                          "kind": "Unauthorized", "retryable": False})
        return False

    # -- routes --------------------------------------------------------
    def do_GET(self):
        try:
            if not self._authorized():
                return
            if self.path == "/healthz":
                alive = self._door.healthy()
                self._reply(200 if alive else 503, {
                    "status": "ok" if alive else "dead",
                    "models": self._door.models(),
                })
            elif self.path == "/stats":
                self._reply(200, self._door.target_stats())
            elif self.path == "/metrics":
                # Prometheus text exposition of the process metrics
                # registry (docs/architecture/observability.md)
                self._reply(200,
                            _metrics.render_prometheus().encode("utf-8"),
                            content_type="text/plain; version=0.0.4")
            elif self.path == "/debug/flight":
                fl = _tracing.flight()
                self._reply(200, {"capacity": fl.capacity,
                                  "events": fl.events()})
            else:
                self._reply(404, {"error": "unknown path %r" % self.path,
                                  "kind": "NotFound", "retryable": False})
        except BrokenPipeError:
            pass
        except BaseException as e:  # noqa: BLE001 — reply, never crash
            self._safe_error(e)

    def do_POST(self):
        try:
            if not self._authorized():
                return
            model, verb = self._split_path()
            if verb == "predict":
                self._serve_predict(model)
            elif verb == "generate":
                self._serve_generate(model)
            else:
                self._reply(404, {"error": "unknown verb %r" % verb,
                                  "kind": "NotFound", "retryable": False})
        except BrokenPipeError:
            pass
        except BaseException as e:  # noqa: BLE001 — reply, never crash
            self._safe_error(e)

    def _safe_error(self, exc):
        try:
            self._reply_error(exc)
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass

    def _split_path(self):
        # /v1/models/<name>:predict
        prefix = "/v1/models/"
        if not self.path.startswith(prefix) or ":" not in self.path:
            raise MXNetError("unknown path %r (want %s<model>:predict "
                             "or :generate)" % (self.path, prefix))
        name, verb = self.path[len(prefix):].rsplit(":", 1)
        return name, verb

    def _serve_predict(self, model):
        """One forward request end to end: parse (JSON or npz), submit
        with the propagated deadline, wait, encode.  The whole span is
        the ``serve_http`` profiler phase — HTTP overhead is the gap
        between it and the engine's serve_* phases."""
        t0 = time.perf_counter_ns()
        ctype = (self.headers.get("Content-Type") or "").split(";")[0]
        body = self._read_body()
        npz = ctype == "application/x-npz"
        try:
            if npz:
                payload = None
                with np.load(io.BytesIO(body), allow_pickle=False) as z:
                    inputs = {k: z[k] for k in z.files}
            else:
                payload = json.loads(body.decode("utf-8"))
                inputs = {k: np.asarray(v)
                          for k, v in payload.get("inputs", {}).items()}
            timeout = self._timeout_s(payload)
            priority, tenant = self._tier_tenant(payload)
        except MXNetError:
            raise
        except Exception as e:  # noqa: BLE001 — client-caused: 400
            raise MXNetError("invalid request body: %s: %s"
                             % (type(e).__name__, e))
        # the request's trace is minted HERE — the network ingress —
        # and stays active across the submit, so every downstream span
        # (balancer dispatch, batch compute) is a child of this trace
        tr = _tracing.start_trace("http.predict", model=model)
        status = "error"
        try:
            with _tracing.activate(tr):
                try:
                    fut = self._door.target.submit(model, timeout=timeout,
                                                   priority=priority,
                                                   tenant=tenant,
                                                   **inputs)
                    outs = fut.result(self._door.wait_budget(timeout))
                except BaseException as e:  # noqa: BLE001 — structured
                    err = self._door.as_serving_error(e)
                    status = type(err).__name__
                    self._reply_error(err)
                    return
                outs = [np.asarray(o) for o in outs]
                if npz:
                    buf = io.BytesIO()
                    np.savez(buf, **{"output_%d" % i: o
                                     for i, o in enumerate(outs)})
                    self._reply(200, buf.getvalue(),
                                content_type="application/x-npz")
                else:
                    self._reply(200, {
                        "outputs": [o.tolist() for o in outs],
                        "shapes": [list(o.shape) for o in outs],
                        "dtypes": [str(o.dtype) for o in outs],
                    })
                _profiler.record_phase("serve_http", t0)
                status = "ok"
        finally:
            tr.finish(status=status)

    def _serve_generate(self, model):
        t0 = time.perf_counter_ns()
        try:
            payload = json.loads(self._read_body().decode("utf-8"))
            timeout = self._timeout_s(payload)
            tokens = payload["tokens"]
            kwargs = {}
            for k in ("max_tokens", "temperature", "top_k", "seed",
                      "eos_id"):
                if payload.get(k) is not None:
                    kwargs[k] = payload[k]
            priority, tenant = self._tier_tenant(payload)
            if priority is not None:
                kwargs["priority"] = priority
            if tenant is not None:
                kwargs["tenant"] = tenant
        except Exception as e:  # noqa: BLE001 — client-caused: 400
            raise MXNetError("invalid request body: %s: %s"
                             % (type(e).__name__, e))
        # generation ingress mints the trace too: the prefill/decode/
        # sample spans of THIS request — across replica placement
        # retries — land under one trace id (the propagation pin)
        tr = _tracing.start_trace("http.generate", model=model)
        status = "error"
        try:
            with _tracing.activate(tr):
                try:
                    fut = self._door.gen_submit(model, tokens,
                                                timeout=timeout, **kwargs)
                    res = fut.result(self._door.wait_budget(timeout))
                except BaseException as e:  # noqa: BLE001 — structured
                    err = self._door.as_serving_error(e)
                    status = type(err).__name__
                    self._reply_error(err)
                    return
                self._reply(200, {
                    "model": res.model,
                    "tokens": [int(t) for t in res.tokens],
                    "finish_reason": res.finish_reason,
                    "prompt_len": int(res.prompt_len),
                    # host perf_counter stamps (CLOCK_MONOTONIC:
                    # comparable across processes on one host) so
                    # same-host clients — and the loadgen — derive
                    # TTFT/ITL exactly like in-process
                    "t_submit": res.t_submit,
                    "token_times": list(res.token_times),
                })
                _profiler.record_phase("serve_http", t0)
                status = "ok"
        finally:
            tr.finish(status=status)


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True


class HttpFrontDoor:
    """HTTP surface over a serving target.

    ``target`` — anything speaking the serving submit contract: a
    :class:`~.scheduler.ServingEngine` or a
    :class:`~.replica_set.ReplicaSet` (whose ``submit_gen`` also backs
    ``:generate``).  ``gen_target`` — an optional separate
    :class:`~.decode_engine.GenerationEngine` when the forward target
    is a bare engine.  ``port=0`` binds an ephemeral port
    (``.address`` reports it).  ``max_wait`` bounds how long a handler
    thread waits on a future with no client deadline.  ``auth_token``
    (default ``MXNET_SERVE_AUTH_TOKEN``; empty = open) requires
    ``Authorization: Bearer <token>`` on every route except
    ``/healthz`` and ``/metrics``.  ``tls_cert`` / ``tls_key``
    (defaults ``MXNET_SERVE_TLS_CERT`` / ``MXNET_SERVE_TLS_KEY``) wrap
    the listening socket in TLS — both PEM paths or neither (one
    without the other is a config error, not silent plaintext);
    ``.url`` reports the scheme."""

    def __init__(self, target, host="127.0.0.1", port=0, gen_target=None,
                 max_wait=300.0, auth_token=None, tls_cert=None,
                 tls_key=None):
        self.target = target
        self._gen_target = gen_target
        if auth_token is None:
            auth_token = get_env("MXNET_SERVE_AUTH_TOKEN") or None
        self.auth_token = auth_token or None
        if tls_cert is None:
            tls_cert = get_env("MXNET_SERVE_TLS_CERT") or None
        if tls_key is None:
            tls_key = get_env("MXNET_SERVE_TLS_KEY") or None
        if bool(tls_cert) != bool(tls_key):
            raise MXNetError(
                "TLS needs BOTH a certificate and a key (set "
                "MXNET_SERVE_TLS_CERT and MXNET_SERVE_TLS_KEY "
                "together); refusing a half-configured endpoint")
        self.tls = bool(tls_cert)
        self._max_wait = float(max_wait)
        self._server = _Server((host, int(port)), _Handler)
        self._server.frontdoor = self
        if self.tls:
            import ssl
            try:
                ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
                ctx.load_cert_chain(tls_cert, tls_key)
                self._server.socket = ctx.wrap_socket(
                    self._server.socket, server_side=True)
            except MXNetError:
                raise
            except Exception as e:
                self._server.server_close()
                raise MXNetError("failed to arm TLS on the front "
                                 "door: %s: %s"
                                 % (type(e).__name__, e)) from e
        # /stats snapshot cache: one stats-tree walk per
        # MXNET_SERVE_STATS_TTL_MS window no matter how many pollers
        # (replies carry age_ms); /healthz's model listing shares it
        self._stats_cache = None
        self._stats_cache_t = 0.0
        self._stats_lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="mxt-http",
            daemon=True)
        self._thread.start()
        self._closed = False

    # -- target shims (handler-side helpers) ---------------------------
    @property
    def address(self):
        host, port = self._server.server_address[:2]
        return (host, port)

    @property
    def url(self):
        return "%s://%s:%d" % ((("https",) if self.tls else ("http",))
                               + self.address)

    def healthy(self):
        alive = getattr(self.target, "alive", None)
        return bool(alive()) if callable(alive) else True

    def models(self):
        # health pollers share the cached stats snapshot when it is
        # fresh instead of re-walking registries per probe
        with self._stats_lock:
            cached, t = self._stats_cache, self._stats_cache_t
        if cached is not None \
                and time.monotonic() - t <= self._stats_ttl():
            m = self._models_from_snapshot(cached)
            if m is not None:
                return m
        t = self.target
        reg = getattr(t, "_registry", None)
        if reg is not None:
            return reg.models()
        reps = getattr(t, "replicas", None)
        if callable(reps):
            for r in reps():
                if r.alive:
                    return r.registry.models()
        return []

    @staticmethod
    def _models_from_snapshot(cached):
        """Model names out of a cached stats tree, for either target
        shape: an engine's top-level ``models`` dict, or a replica
        set's ``replicas -> {i: {alive, engine: {models}}}`` nesting
        (first live replica wins — replicas are shared-nothing copies
        of the same registry).  None = shape unknown, walk instead."""
        m = cached.get("models")
        if isinstance(m, dict):
            return sorted(m)
        reps = cached.get("replicas")
        if isinstance(reps, dict):
            for r in reps.values():
                if not isinstance(r, dict) or not r.get("alive", False):
                    continue
                em = r.get("engine", {}).get("models")
                if isinstance(em, dict):
                    return sorted(em)
            return []
        return None

    @staticmethod
    def _stats_ttl():
        return max(0.0, float(get_env("MXNET_SERVE_STATS_TTL_MS"))) / 1e3

    def target_stats(self):
        """The target's stats tree, served from a TTL-bounded cache:
        a poll within ``MXNET_SERVE_STATS_TTL_MS`` of the last walk
        returns the cached snapshot (its ``age_ms`` field says how
        stale) instead of re-walking every engine/replica/store stats
        surface per request."""
        now = time.monotonic()
        with self._stats_lock:
            if self._stats_cache is None \
                    or now - self._stats_cache_t > self._stats_ttl():
                self._stats_cache = self.target.stats()
                self._stats_cache_t = now
            out = dict(self._stats_cache)
            out["age_ms"] = round((now - self._stats_cache_t) * 1e3, 3)
        return out

    def gen_submit(self, model, tokens, **kwargs):
        # an EXPLICIT gen_target wins over the forward target's own
        # submit_gen (a forward-only ReplicaSet can front a separate
        # generation engine)
        if self._gen_target is not None:
            return self._gen_target.submit(model, tokens, **kwargs)
        if hasattr(self.target, "submit_gen"):
            return self.target.submit_gen(model, tokens, **kwargs)
        raise MXNetError("this front door serves no generation target")

    def wait_budget(self, timeout):
        """How long a handler thread waits on the future: the client's
        deadline plus compute grace, else the server-wide cap."""
        if timeout is None:
            return self._max_wait
        return timeout + self._max_wait

    def as_serving_error(self, exc):
        """Normalize waiting errors: a Future.result timeout becomes
        ServeTimeout (the handler out-waited the deadline + grace)."""
        import concurrent.futures
        if isinstance(exc, concurrent.futures.TimeoutError):
            return ServeTimeout("request did not complete within the "
                                "front door's wait budget")
        if isinstance(exc, concurrent.futures.CancelledError):
            return ServeClosed("request was cancelled")
        return exc

    def close(self, timeout=30.0):
        """Stop accepting, join the acceptor thread.  In-flight handler
        threads (daemon) finish their replies on their own."""
        if self._closed:
            return
        self._closed = True
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# Client + loadgen transport adapter
# ---------------------------------------------------------------------------
class HttpClient:
    """Future-returning HTTP client for the front door.

    A pool of worker threads holds one persistent connection each;
    ``submit`` / ``generate`` enqueue a request and return a
    ``concurrent.futures.Future``, so the SAME seeded
    ``OpenLoopSchedule`` + ``run_loadgen`` machinery that drives
    in-process engines drives an HTTP front door — the transport is the
    only variable (the ``serving.frontdoor.http_overhead`` bench row's
    whole point).  Error replies map back to the serving exception
    classes, so the loadgen's timeout/error classification is
    transport-invariant.  ``auth_token`` (default
    ``MXNET_SERVE_AUTH_TOKEN``) rides every request as a bearer
    credential.  ``tls`` turns the connections into TLS (inferred from
    an ``https://`` address string, e.g. a TLS front door's ``.url``);
    ``tls_verify`` (default ``MXNET_SERVE_TLS_VERIFY``) is ``"1"`` for
    the system trust store, ``"0"`` to skip verification, or a PEM
    path pinning the accepted CA/certificate (how a client trusts a
    self-signed front door without disabling verification)."""

    def __init__(self, address, threads=8, connect_timeout=120.0,
                 auth_token=None, tls=None, tls_verify=None):
        if isinstance(address, str):
            if tls is None and address.startswith("https://"):
                tls = True
            host, port = address.rsplit(":", 1)
            address = (host.replace("https://", "")
                       .replace("http://", "").strip("/"), int(port))
        self._addr = (address[0], int(address[1]))
        self._tls = bool(tls)
        self._ssl_ctx = self._tls_context(tls_verify) if self._tls \
            else None
        if auth_token is None:
            auth_token = get_env("MXNET_SERVE_AUTH_TOKEN") or None
        self._auth_token = auth_token or None
        self._timeout = float(connect_timeout)
        self._closed = False
        self._close_lock = threading.Lock()
        self._q = queue.Queue()
        self._threads = []
        for i in range(int(threads)):
            t = threading.Thread(target=self._worker,
                                 name="mxt-http-client-%d" % i,
                                 daemon=True)
            t.start()
            self._threads.append(t)

    # -- public --------------------------------------------------------
    def submit(self, model, inputs, timeout=None, priority=None,
               tenant=None):
        """One forward request over npz transport; returns a Future
        resolving to the list of output arrays (bit-exact: no JSON
        float round-trip).  ``priority`` / ``tenant`` ride the
        ``X-Mxnet-Priority`` / ``X-Mxnet-Tenant`` headers into the
        engine's tiered admission."""
        buf = io.BytesIO()
        np.savez(buf, **{k: np.asarray(v) for k, v in inputs.items()})
        headers = {"Content-Type": "application/x-npz"}
        if timeout is not None:
            headers["X-Mxnet-Timeout-Ms"] = "%g" % (timeout * 1e3)
        if priority is not None:
            headers["X-Mxnet-Priority"] = str(priority)
        if tenant is not None:
            headers["X-Mxnet-Tenant"] = str(tenant)
        return self._enqueue("POST", "/v1/models/%s:predict" % model,
                             buf.getvalue(), headers, self._parse_npz)

    def submit_json(self, model, inputs, timeout=None, priority=None,
                    tenant=None):
        """The curl-shaped JSON variant (lists in, lists out)."""
        payload = {"inputs": {k: np.asarray(v).tolist()
                              for k, v in inputs.items()}}
        if timeout is not None:
            payload["timeout_ms"] = timeout * 1e3
        if priority is not None:
            payload["priority"] = priority
        if tenant is not None:
            payload["tenant"] = tenant
        return self._enqueue(
            "POST", "/v1/models/%s:predict" % model,
            json.dumps(payload).encode("utf-8"),
            {"Content-Type": "application/json"}, self._parse_json)

    def generate(self, model, tokens, timeout=None, **kwargs):
        """One generation request; the Future resolves to a
        :class:`~.decode_engine.GenerationResult` rebuilt from the
        reply (token_times are host-monotonic stamps, comparable on
        the same host)."""
        payload = {"tokens": [int(t) for t in tokens]}
        payload.update(kwargs)
        if timeout is not None:
            payload["timeout_ms"] = timeout * 1e3
        # retryable=False: a generation is NOT idempotent — a
        # redial-resend after the server already admitted it would
        # double-execute (the replica set's own no-retry-after-
        # admission contract, applied to the transport)
        return self._enqueue(
            "POST", "/v1/models/%s:generate" % model,
            json.dumps(payload).encode("utf-8"),
            {"Content-Type": "application/json"}, self._parse_gen,
            retryable=False)

    def healthz(self):
        """Synchronous health check: (status_code, payload dict)."""
        fut = self._enqueue("GET", "/healthz", None, {}, self._parse_raw)
        return fut.result(self._timeout)

    def stats(self):
        fut = self._enqueue("GET", "/stats", None, {}, self._parse_raw)
        code, payload = fut.result(self._timeout)
        if code != 200:
            raise MXNetError("stats failed: HTTP %d" % code)
        return payload

    def metrics_text(self):
        """``GET /metrics``: the Prometheus text exposition."""
        fut = self._enqueue("GET", "/metrics", None, {},
                            lambda status, body: (status, body))
        code, body = fut.result(self._timeout)
        if code != 200:
            raise MXNetError("metrics failed: HTTP %d" % code)
        return body.decode("utf-8")

    def debug_flight(self):
        """``GET /debug/flight``: the server's flight-recorder ring."""
        fut = self._enqueue("GET", "/debug/flight", None, {},
                            self._parse_raw)
        code, payload = fut.result(self._timeout)
        if code != 200:
            raise MXNetError("debug/flight failed: HTTP %d" % code)
        return payload

    def close(self):
        with self._close_lock:
            # the lock orders every _enqueue strictly before or after
            # the flag: after it, _enqueue raises, so nothing can land
            # behind the sentinels or after the drain below
            self._closed = True
            for _ in self._threads:
                self._q.put(None)
        for t in self._threads:
            t.join(30)
        # anything enqueued before close() but behind a sentinel is
        # unreachable by the workers: fail its future instead of
        # leaving the caller pending forever
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                return
            if item is not None:
                try:
                    item[-1].set_exception(
                        ServeClosed("HttpClient is closed"))
                except InvalidStateError:
                    pass    # caller cancelled while we drained

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- worker pool ---------------------------------------------------
    @staticmethod
    def _tls_context(verify):
        """Client-side SSL context from the verify knob: ``"1"`` =
        system trust store, ``"0"`` = no verification (lab use),
        anything else = a PEM path pinning the accepted certificate
        chain (the self-signed deployment's knob)."""
        import ssl
        if verify is None:
            verify = get_env("MXNET_SERVE_TLS_VERIFY")
        verify = str(verify if verify is not None else "1") or "1"
        if verify == "0":
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
            return ctx
        if verify == "1":
            return ssl.create_default_context()
        try:
            return ssl.create_default_context(cafile=verify)
        except Exception as e:
            raise MXNetError(
                "MXNET_SERVE_TLS_VERIFY=%r is neither 0/1 nor a "
                "readable PEM: %s: %s"
                % (verify, type(e).__name__, e)) from e

    def _connect(self):
        """One fresh connection honoring the TLS mode."""
        import http.client
        if self._tls:
            return http.client.HTTPSConnection(
                *self._addr, timeout=self._timeout,
                context=self._ssl_ctx)
        return http.client.HTTPConnection(*self._addr,
                                          timeout=self._timeout)

    def _enqueue(self, method, path, body, headers, parse,
                 retryable=True):
        if self._auth_token and "Authorization" not in headers:
            headers = dict(headers)
            headers["Authorization"] = "Bearer %s" % self._auth_token
        with self._close_lock:
            if self._closed:
                raise ServeClosed("HttpClient is closed")
            fut = Future()
            self._q.put((method, path, body, headers, parse, retryable,
                         fut))
        return fut

    @staticmethod
    def _parse_npz(status, body):
        if status != 200:
            raise _decode_error(status, body)
        with np.load(io.BytesIO(body), allow_pickle=False) as z:
            return [z["output_%d" % i] for i in range(len(z.files))]

    @staticmethod
    def _parse_json(status, body):
        if status != 200:
            raise _decode_error(status, body)
        d = json.loads(body.decode("utf-8"))
        return [np.asarray(o, dtype=dt).reshape(sh) for o, sh, dt in
                zip(d["outputs"], d["shapes"], d["dtypes"])]

    @staticmethod
    def _parse_gen(status, body):
        if status != 200:
            raise _decode_error(status, body)
        d = json.loads(body.decode("utf-8"))
        from .decode_engine import GenerationResult
        return GenerationResult(d["model"], d["prompt_len"], d["tokens"],
                                d["finish_reason"], d["t_submit"],
                                d["token_times"])

    @staticmethod
    def _parse_raw(status, body):
        try:
            return status, json.loads(body.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            return status, None

    def _worker(self):
        import http.client
        conn = None
        while True:
            item = self._q.get()
            if item is None:
                if conn is not None:
                    conn.close()
                return
            method, path, body, headers, parse, retryable, fut = item
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                if retryable:
                    for attempt in (0, 1):
                        if conn is None:
                            conn = self._connect()
                        try:
                            conn.request(method, path, body=body,
                                         headers=headers)
                            resp = conn.getresponse()
                            payload = resp.read()
                            break
                        except (http.client.HTTPException, OSError):
                            # stale persistent connection: redial once
                            # (idempotent requests only — a resend
                            # cannot double-execute a pure forward)
                            conn.close()
                            conn = None
                            if attempt:
                                raise
                else:
                    # non-idempotent (:generate): ONE attempt on a
                    # FRESH connection — no stale-keepalive failure
                    # mode, and never a retransmit the server might
                    # have already admitted
                    c2 = self._connect()
                    try:
                        c2.request(method, path, body=body,
                                   headers=headers)
                        resp = c2.getresponse()
                        payload = resp.read()
                    finally:
                        c2.close()
                fut.set_result(parse(resp.status, payload))
            except BaseException as e:  # noqa: BLE001 — to the future
                try:
                    fut.set_exception(e)
                except Exception:  # InvalidStateError: cancel raced
                    pass

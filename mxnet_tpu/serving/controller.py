"""Serving control plane: the SLO-driven autoscaler.

PR 13 built the actuators (an elastic :class:`~.replica_set.ReplicaSet`
with health-checked failover and hot swap) and PR 14 built the sensors
(queue-wait histograms, shed counters, inflight gauges in one metrics
registry).  :class:`AutoScaler` closes the loop: a controller thread
ticks every ``MXNET_SERVE_AUTOSCALE_INTERVAL`` seconds, reads the
sensors, and grows or shrinks the replica set so the queue-wait p95
stays under the SLO target with as few replicas as the load allows.

Signals per tick (all WINDOWED — deltas since the previous tick, via
:class:`~..metrics.HistogramWindow`; a burst an hour ago must not pin
the controller's view forever):

* queue-wait p95 of the window vs ``MXNET_SERVE_SLO_MS``;
* the shed-counter delta (admission control firing means the set is
  saturated NOW, whatever the latency histogram says);
* inflight utilization (balancer-tracked inflight over the aggregate
  engine budget, when the engines are bounded).

State machine (evaluate_once)::

        ┌─────────────── hold ───────────────┐
        │                                    │
        ▼   p95 > SLO  or  sheds > 0         │
    [steady] ─── or util > up_util ──▶ [scale up]───▶ +1 replica
        │                                  (cooldown gates the NEXT
        │   p95 < SLO * down_frac           action, not observation)
        │   and sheds == 0
        └── and util < down_util ────▶ [scale down]─▶ -1 replica

Hysteresis: the scale-down band (``down_frac`` of the SLO, low
utilization, zero sheds) is far below the scale-up trigger, and every
action arms a shared cool-down (``MXNET_SERVE_AUTOSCALE_COOLDOWN``), so
a diurnal swing walks the set up and back down instead of flapping.

The controller thread is deliberately NON-daemon: close() must join it
(the test suite's thread-leak gate enforces the discipline), and it
appears in ``threading.enumerate()`` as ``mxt-serve-autoscale``.

Scale-up builds a replica from the set's registry factory — weight
loading happens on the controller thread, never on a dispatch path;
scale-down removes the youngest live replica WITH drain, so downsizing
under traffic loses nothing.  Replica-seconds are integrated across the
whole run (``replica_seconds()``): the bench rows compare them against
static max-size provisioning to price the autoscaler's savings.
"""
from __future__ import annotations

import logging
import threading
import time

from .. import metrics as _metrics
from .. import tracing as _tracing
from ..base import MXNetError, get_env
from .scheduler import _H_QWAIT

log = logging.getLogger(__name__)

__all__ = ["AutoScaler"]


class AutoScaler:
    """Closed-loop replica-count controller over a
    :class:`~.replica_set.ReplicaSet`.

    Parameters
    ----------
    rset : ReplicaSet
        The set to control; it must have been built with a callable
        ``build_registry`` (growth rebuilds registries from it).
    slo_ms : float, optional
        Queue-wait p95 target; default ``MXNET_SERVE_SLO_MS``.
    min_replicas / max_replicas : int, optional
        Size bounds; defaults ``MXNET_SERVE_MIN_REPLICAS`` /
        ``MXNET_SERVE_MAX_REPLICAS``.
    interval : float, optional
        Tick period (seconds) of the controller thread; default
        ``MXNET_SERVE_AUTOSCALE_INTERVAL``.
    cooldown : float, optional
        Minimum seconds between scale actions; default
        ``MXNET_SERVE_AUTOSCALE_COOLDOWN``.
    down_frac : float
        Hysteresis: scale down only when the window p95 is under
        ``slo_ms * down_frac`` (and no sheds, and low utilization).
    up_util / down_util : float
        Inflight-utilization thresholds (used only when every engine
        has a bounded ``max_inflight``).
    start : bool, optional
        Start the controller thread.  ``None`` (default) follows
        ``MXNET_SERVE_AUTOSCALE``; pass ``True``/``False`` to decide
        explicitly.  An unstarted controller is still fully usable
        through :meth:`evaluate_once` (tests drive it clock-free).
    """

    def __init__(self, rset, slo_ms=None, min_replicas=None,
                 max_replicas=None, interval=None, cooldown=None,
                 down_frac=0.5, up_util=0.85, down_util=0.35,
                 start=None):
        self._rset = rset
        if slo_ms is None:
            slo_ms = float(get_env("MXNET_SERVE_SLO_MS"))
        if min_replicas is None:
            min_replicas = int(get_env("MXNET_SERVE_MIN_REPLICAS"))
        if max_replicas is None:
            max_replicas = int(get_env("MXNET_SERVE_MAX_REPLICAS"))
        if interval is None:
            interval = float(get_env("MXNET_SERVE_AUTOSCALE_INTERVAL"))
        if cooldown is None:
            cooldown = float(get_env("MXNET_SERVE_AUTOSCALE_COOLDOWN"))
        self.slo_ms = float(slo_ms)
        self.min_replicas = max(1, int(min_replicas))
        self.max_replicas = max(self.min_replicas, int(max_replicas))
        self.interval = max(0.01, float(interval))
        self.cooldown = max(0.0, float(cooldown))
        self.down_frac = float(down_frac)
        self.up_util = float(up_util)
        self.down_util = float(down_util)
        if self.min_replicas > 1 or self.max_replicas > rset.n_replicas():
            # growth needs the factory; fail at construction, not at
            # the first scale-up tick inside the controller thread
            if rset._build is None:
                raise MXNetError(
                    "AutoScaler needs a ReplicaSet built with a "
                    "callable build_registry (growth reloads weights)")
        self._qwait = _metrics.HistogramWindow(_H_QWAIT)
        sig = rset.load_signals()
        self._prev_shed = sig["shed_total"]
        now = time.monotonic()
        self._last_action = now - self.cooldown   # first tick may act
        self._rs_t = now                          # replica-seconds mark
        self._rs_total = 0.0
        self._actions = []   # (t_monotonic, "up"/"down", n_after)
        labels = dict(rset._mlabels)
        self._g_replicas = _metrics.gauge(
            "serve_autoscale_replicas", labels=labels,
            help="replica count the autoscaler is holding")
        self._g_p95 = _metrics.gauge(
            "serve_autoscale_qwait_p95_ms", labels=labels,
            help="windowed queue-wait p95 the last tick judged")
        self._c_up = _metrics.counter(
            "serve_autoscale_up_total", labels=labels,
            help="autoscaler scale-up actions")
        self._c_down = _metrics.counter(
            "serve_autoscale_down_total", labels=labels,
            help="autoscaler scale-down actions")
        self._g_replicas.set(sig["n_replicas"])
        self._closed = False
        self._stop = threading.Event()
        self._thread = None
        if start is None:
            start = bool(int(get_env("MXNET_SERVE_AUTOSCALE")))
        if start:
            # non-daemon ON PURPOSE: close() joins it, and the test
            # suite's leak gate fails any test that forgets to
            # graft-lint: disable=thread-discipline — stop-event + join live in close()
            self._thread = threading.Thread(
                target=self._run, name="mxt-serve-autoscale",
                daemon=False)
            self._thread.start()

    # -- controller thread ---------------------------------------------
    def _run(self):
        while not self._stop.wait(self.interval):
            try:
                self.evaluate_once()
            except BaseException as e:  # noqa: BLE001 — keep ticking
                # a failed scale action (e.g. the set closed under us)
                # must not kill the controller; the flight ring keeps
                # the evidence
                _tracing.flight().record(
                    "error", "autoscaler tick failed", error=repr(e))

    def evaluate_once(self, now=None):
        """One controller tick: window the sensors, apply the state
        machine, actuate at most one scale step.  Returns a dict of
        the signals and the action taken (tests and the chaos campaign
        assert on it)."""
        if now is None:
            now = time.monotonic()
        # integrate replica-seconds at the PRE-action size: the segment
        # since the last tick ran at that size
        sig = self._rset.load_signals()
        self._rs_total += (now - self._rs_t) * sig["n_replicas"]
        self._rs_t = now
        count, _, quantile = self._qwait.tick()
        p95 = quantile(0.95)
        p95_ms = None if p95 is None else p95 * 1e3
        shed_delta = sig["shed_total"] - self._prev_shed
        self._prev_shed = sig["shed_total"]
        util = None
        if sig["capacity"]:
            util = sig["inflight"] / float(sig["capacity"])
        self._g_p95.set(p95_ms if p95_ms is not None else 0.0)
        n = sig["n_replicas"]
        action = "hold"
        cooled = (now - self._last_action) >= self.cooldown
        over = ((p95_ms is not None and p95_ms > self.slo_ms)
                or shed_delta > 0
                or (util is not None and util > self.up_util))
        under = ((p95_ms is None or p95_ms < self.slo_ms
                  * self.down_frac)
                 and shed_delta == 0
                 and (util is None or util < self.down_util))
        if over and n < self.max_replicas and cooled:
            self._rset.add_replica()
            self._c_up.inc()
            action = "up"
        elif not over and under and n > self.min_replicas and cooled:
            self._rset.remove_replica(drain=True)
            self._c_down.inc()
            action = "down"
        if action != "hold":
            n = self._rset.n_replicas()
            self._last_action = now
            self._actions.append((now, action, n))
            self._g_replicas.set(n)
            log.info("autoscaler: scale %s to %d replicas (p95=%sms "
                     "slo=%.1fms sheds=%d util=%s)", action, n,
                     "%.1f" % p95_ms if p95_ms is not None else "-",
                     self.slo_ms, shed_delta,
                     "%.2f" % util if util is not None else "-")
        return {"action": action, "n_replicas": n, "p95_ms": p95_ms,
                "window_count": count, "shed_delta": shed_delta,
                "util": util}

    # -- accounting ----------------------------------------------------
    def replica_seconds(self, now=None):
        """Replica-seconds integrated since construction (including the
        still-open segment): the provisioning cost the bench rows
        compare against static max-size serving."""
        if now is None:
            now = time.monotonic()
        return self._rs_total + \
            (now - self._rs_t) * self._rset.n_replicas()

    def actions(self):
        """The scale-action history: (monotonic time, 'up'/'down',
        replica count after)."""
        return list(self._actions)

    def close(self, timeout=30.0):
        """Stop and JOIN the controller thread (idempotent).  The set
        itself is not closed — the controller only borrows it."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                raise MXNetError("autoscaler thread failed to stop "
                                 "within %.0fs" % timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

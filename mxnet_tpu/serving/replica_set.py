"""Shared-nothing multi-replica serving with health-checked failover.

One replica = one private :class:`~.registry.ModelRegistry` plus its own
:class:`~.scheduler.ServingEngine` (and optionally a
:class:`~.decode_engine.GenerationEngine`): no weights, caches, program
stores or queues are shared between replicas, so a replica dying takes
down exactly its own state — the shared-nothing failure unit the
training side's parameter servers already are.

:class:`ReplicaSet` fronts N replicas with a **least-loaded balancer**:

* every dispatch (request or health probe) crosses the
  ``serve.dispatch`` faultinject seam, so seeded schedules can drop /
  delay / sever / SIGKILL a replica deterministically (``die`` at this
  seam kills the targeted REPLICA in-process via the registered die
  handler instead of exiting the test process);
* each replica carries a :class:`~..retry.CircuitBreaker` (the PR-2
  kvstore plane's breaker, factored into ``mxnet_tpu/retry.py``):
  consecutive dispatch/probe failures open it and the balancer routes
  around the replica without paying its failure latency;
* **forward** requests are idempotent (pure bucketed forward), so a
  dispatch that fails retryably — the replica died, its engine closed,
  the connection severed — is retried with bounded
  exponential backoff (``mxnet_tpu.retry.backoff_delay``;
  ``MXNET_SERVE_RETRIES`` / ``MXNET_SERVE_RETRY_BACKOFF``) onto a
  SURVIVING replica, excluding every replica already observed failing
  for that request;
* **generation** requests fail fast once admitted: their KV cache died
  with the replica and silently regenerating would replay the sampled
  stream from scratch — the client gets a structured, retryable
  :class:`ReplicaDied` and decides (before admission — the dispatch
  itself failing — they retry like forwards, nothing is lost yet);
* a **prober** thread re-probes every replica each
  ``MXNET_SERVE_PROBE_INTERVAL`` seconds: probe failures open the
  breaker (a dead replica leaves the rotation within one interval),
  probe successes close it again (a transiently severed replica
  returns).

Hot weight swap ROLLS: :meth:`ReplicaSet.swap_params` republishes the
new weights one replica at a time — take the replica out of rotation
(while the others carry the traffic), drain its inflight requests, swap,
re-probe, restore — with abort-and-rollback when a re-probe fails, so a
bad weight set never takes more than one replica out.  Each replica's
store-level swap stays atomic per request (``program_store.swap_params``),
which is what lets a one-replica set swap in place.

The set is ELASTIC: :meth:`ReplicaSet.add_replica` /
:meth:`ReplicaSet.remove_replica` grow and shrink it under traffic
(replica indices are monotonic and never reused), which is the actuator
arm of the serving autoscaler (``serving/controller.py``) —
:meth:`ReplicaSet.load_signals` is its sensor arm.

Admission control composes: each replica's engine sheds with
:class:`~.scheduler.ServeOverloaded` at its ``MXNET_SERVE_MAX_INFLIGHT``
budget; the balancer treats a shed as "try the next replica" and only
surfaces 429 to the client when EVERY live replica is at budget.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future, InvalidStateError

from .. import faultinject
from .. import metrics as _metrics
from .. import profiler as _profiler
from .. import tracing as _tracing
from ..analysis import racecheck
from ..analysis.lockcheck import make_lock
from ..base import MXNetError, _uid, get_env
from ..retry import CircuitBreaker, backoff_delay

# breaker-state gauge encoding (serve_replica_breaker{replica=...})
_BREAKER_STATES = {"closed": 0, "half-open": 1, "half_open": 1,
                   "open": 2}
from .registry import ModelRegistry
from .scheduler import (ServeClosed, ServeOverloaded, ServeTimeout,
                        ServingEngine)

__all__ = ["Replica", "ReplicaSet", "ReplicaDied", "NoLiveReplicas"]

SEAM = "serve.dispatch"


class ReplicaDied(MXNetError):
    """The replica serving (or about to serve) this request died.

    Retryable by contract: the balancer retries forward requests onto a
    survivor automatically; a generation request admitted to the dead
    replica surfaces this to the client (its KV state is gone — the
    client owns the resubmit decision)."""


class NoLiveReplicas(MXNetError):
    """Every replica is dead, breaker-open, or excluded by this
    request's failure history; nothing can serve it."""


class Replica:
    """One shared-nothing serving unit: a private registry + engines.

    ``populate(registry)`` happened before construction — the caller
    builds and fills the registry (each replica loads its OWN copy of
    the weights; nothing is shared).  ``gen=True`` also starts a
    GenerationEngine over the same registry."""

    def __init__(self, index, registry, gen=False, max_delay_ms=None,
                 max_batch=None, max_inflight=None, breaker=None,
                 tenant_quotas=None):
        self.index = int(index)
        self.registry = registry
        # owner_index: every ServeClosed this replica's engines mint
        # names the replica, so the retry layer and flight recorder
        # know exactly which replica died out from under a request
        self.engine = ServingEngine(registry, max_delay_ms=max_delay_ms,
                                    max_batch=max_batch,
                                    max_inflight=max_inflight,
                                    owner_index=self.index,
                                    tenant_quotas=tenant_quotas)
        self.gen_engine = None
        if gen:
            from .decode_engine import GenerationEngine
            self.gen_engine = GenerationEngine(
                registry, max_inflight=max_inflight,
                owner_index=self.index, tenant_quotas=tenant_quotas)
        if breaker is None:
            # default from the SERVING knobs — the shared
            # CircuitBreaker's own constructor defaults belong to the
            # kvstore plane
            breaker = CircuitBreaker(
                fail_threshold=int(get_env("MXNET_SERVE_CB_FAILS")),
                reset_after=float(get_env("MXNET_SERVE_CB_RESET")))
        self.breaker = breaker
        self.inflight = 0           # balancer-tracked, set-lock guarded
        # liveness flags live in a racecheck.shared_state container,
        # read/written only through the lock-guarded properties below:
        # kill()/close() (any thread), the prober, the balancer's
        # comprehensions and the rolling swap all order through
        # _life_lock, and MXNET_RACE_CHECK=1 flags any future path
        # that skips it.  RLock: kill/close read-modify under it while
        # the properties re-acquire
        self._rc = racecheck.shared_state(
            "serving.replica%d" % self.index, alive=True, draining=False)
        self._life_lock = make_lock("serving.replica", rlock=True)

    @property
    def alive(self):
        with self._life_lock:
            return self._rc.alive

    @alive.setter
    def alive(self, v):
        with self._life_lock:
            self._rc.alive = bool(v)

    @property
    def draining(self):
        with self._life_lock:
            return self._rc.draining

    @draining.setter
    def draining(self, v):
        with self._life_lock:
            self._rc.draining = bool(v)

    def kill(self):
        """Simulated SIGKILL: the replica stops abruptly.  Queued and
        forming work fails fast with ServeClosed (the balancer maps it
        to a retryable failover); in-flight generations lose their KV
        state.  Idempotent; callable from any non-engine thread."""
        with self._life_lock:
            if not self.alive:
                return
            self.alive = False
        # drain=False: fail-fast close, the in-process analog of the
        # process vanishing (dispatched device work completes — a real
        # SIGKILL would also leave the accelerator step finishing)
        self.engine.close(drain=False)
        if self.gen_engine is not None:
            self.gen_engine.close(drain=False)

    def close(self, drain=True):
        """Graceful stop (drains by default); used by ReplicaSet.close."""
        with self._life_lock:
            already_dead = not self.alive
            self.alive = False
        if already_dead:
            return
        self.engine.close(drain=drain)
        if self.gen_engine is not None:
            self.gen_engine.close(drain=drain)


class ReplicaSet:
    """Least-loaded balancer + failover over N shared-nothing replicas.

    Parameters
    ----------
    build_registry : callable(index) -> ModelRegistry, or list
        Factory producing each replica's PRIVATE registry (load the
        same checkpoint N times — replicas share nothing), or an
        explicit list of pre-built registries.
    n_replicas : int
        Replica count (ignored when a list is passed).
    gen : bool
        Also run a GenerationEngine per replica.
    retries / backoff : int / float, optional
        Forward failover policy; default ``MXNET_SERVE_RETRIES`` /
        ``MXNET_SERVE_RETRY_BACKOFF`` (backoff cap is 16x the base).
    cb_fails / cb_reset : optional
        Per-replica breaker thresholds; default ``MXNET_SERVE_CB_FAILS``
        / ``MXNET_SERVE_CB_RESET``.
    probe_interval : float, optional
        Health-probe period (seconds); default
        ``MXNET_SERVE_PROBE_INTERVAL``.  ``<= 0`` disables the prober.
    max_delay_ms / max_batch / max_inflight :
        Passed through to every replica's engine(s).
    spares : int, optional
        Warm spare-registry pool size.  ``spares`` extra registries are
        built (weights loaded, programs compiled) at construction;
        :meth:`add_replica` joins one to the rotation WITHOUT compiling
        on the caller's thread — the autoscaler's scale-up completes in
        milliseconds instead of a weight-load.  :meth:`remove_replica`
        recycles the drained registry back into the pool (up to
        ``spares``), so a diurnal swing pays the build cost once.
        Requires a callable ``build_registry``; spare builds see a
        provisional index (the factory's index argument is advisory).
    """

    def __init__(self, build_registry, n_replicas=3, gen=False,
                 retries=None, backoff=None, cb_fails=None, cb_reset=None,
                 probe_interval=None, max_delay_ms=None, max_batch=None,
                 max_inflight=None, tenant_quotas=None, spares=0):
        if retries is None:
            retries = int(get_env("MXNET_SERVE_RETRIES"))
        if backoff is None:
            backoff = float(get_env("MXNET_SERVE_RETRY_BACKOFF"))
        if cb_fails is None:
            cb_fails = int(get_env("MXNET_SERVE_CB_FAILS"))
        if cb_reset is None:
            cb_reset = float(get_env("MXNET_SERVE_CB_RESET"))
        if probe_interval is None:
            probe_interval = float(get_env("MXNET_SERVE_PROBE_INTERVAL"))
        self._retries = max(0, int(retries))
        self._backoff = max(0.0, float(backoff))
        self._probe_interval = float(probe_interval)
        # the factory and engine knobs are KEPT: add_replica() builds
        # new replicas from them (elastic sizing needs to reload the
        # weights — replicas share nothing)
        self._build = None if isinstance(build_registry, (list, tuple)) \
            else build_registry
        self._gen = bool(gen)
        self._cb_fails = int(cb_fails)
        self._cb_reset = float(cb_reset)
        self._max_delay_ms = max_delay_ms
        self._max_batch = max_batch
        self._max_inflight = max_inflight
        self._tenant_quotas = tenant_quotas
        if self._build is None:
            registries = list(build_registry)
        else:
            registries = [build_registry(i) for i in range(n_replicas)]
        if not registries:
            raise MXNetError("a ReplicaSet needs at least one replica")
        for i, reg in enumerate(registries):
            if not isinstance(reg, ModelRegistry):
                raise MXNetError("replica %d: build_registry must yield "
                                 "a ModelRegistry, got %r" % (i, reg))
        self._replicas = [self._new_replica(i, reg)
                          for i, reg in enumerate(registries)]
        # replica indices are monotonic and NEVER reused across
        # grow/shrink: metrics labels, flight records and faultinject
        # sid matches stay unambiguous over the set's whole life
        self._next_index = len(registries)
        self._spare_cap = max(0, int(spares))
        if self._spare_cap and self._build is None:
            raise MXNetError(
                "a spare pool needs a callable build_registry "
                "(spares are prebuilt from the factory)")
        self._spares = [self._build(self._next_index + k)
                        for k in range(self._spare_cap)]
        for k, reg in enumerate(self._spares):
            if not isinstance(reg, ModelRegistry):
                raise MXNetError("spare %d: build_registry must yield "
                                 "a ModelRegistry, got %r" % (k, reg))
        self._lock = make_lock("serving.replica_set")
        # counters live in the process metrics registry (labeled per
        # set); stats() reads THROUGH them.  Per-replica liveness and
        # breaker state are gauges keyed by replica index.
        self._mlabels = {"rset": "rs%d" % _uid()}
        self._stats = _metrics.CounterDict(
            "serve_rs_",
            ("submitted", "dispatched", "retries", "failovers", "shed",
             "no_live", "probe_failures", "gen_submitted",
             "gen_aborted", "replica_deaths"),
            labels=self._mlabels, help="serving replica-set counter")
        for r in self._replicas:
            self._note_breaker(r)
        self._closed = False
        # the in-process SIGKILL: a scheduled `die` at the
        # serve.dispatch seam kills the TARGETED replica (meta carries
        # sid) and fails the triggering dispatch like a severed
        # connection — os._exit would take the whole test process
        faultinject.register_die_handler(SEAM, self._injected_die)
        self._probe_stop = threading.Event()
        self._prober = None
        if self._probe_interval > 0:
            self._prober = threading.Thread(target=self._probe_loop,
                                            name="mxt-serve-probe",
                                            daemon=True)
            self._prober.start()

    def _new_replica(self, index, reg):
        return Replica(index, reg, gen=self._gen,
                       max_delay_ms=self._max_delay_ms,
                       max_batch=self._max_batch,
                       max_inflight=self._max_inflight,
                       tenant_quotas=self._tenant_quotas,
                       breaker=CircuitBreaker(
                           fail_threshold=self._cb_fails,
                           reset_after=self._cb_reset))

    def _replica(self, index):
        """Replica by its STABLE index (not list position — grow/shrink
        reorders the list); None when no such replica remains."""
        with self._lock:
            for r in self._replicas:
                if r.index == index:
                    return r
        return None

    def _note_breaker(self, r):
        """Publish one replica's breaker state + liveness as gauges
        (called on probe sweeps and failure transitions — the scrape's
        view of the rotation)."""
        labels = dict(self._mlabels, replica=str(r.index))
        _metrics.gauge("serve_replica_breaker", labels=labels,
                       help="0=closed 1=half-open 2=open").set(
            _BREAKER_STATES.get(str(r.breaker.state), -1))
        _metrics.gauge("serve_replica_alive", labels=labels,
                       help="1 while the replica can serve").set(
            1 if r.alive else 0)

    def _note_death(self, index, how):
        """One replica died: count it, flight-record it, and dump the
        postmortem artifact NAMING the dead replica (the PR-13
        kill-one-under-load scenario's readable evidence)."""
        self._stats.inc("replica_deaths")
        fl = _tracing.flight()
        fl.record("replica_died", "replica %s" % index,
                  sid=index, how=how,
                  live=[r.index for r in self._replicas if r.alive])
        fl.dump(reason="replica %s died (%s)" % (index, how))

    # -- faultinject ---------------------------------------------------
    def _injected_die(self, meta):
        sid = meta.get("sid")
        r = self._replica(int(sid)) if sid is not None else None
        if r is not None:
            was_alive = r.alive
            r.kill()
            if was_alive:
                self._note_death(r.index, "injected die at %s" % SEAM)
                self._note_breaker(r)
        raise ReplicaDied("replica %s died (injected at %s)"
                          % (sid, SEAM))

    # -- balancer ------------------------------------------------------
    def _pick(self, excluded):
        """Least-loaded live replica whose breaker admits a call; None
        when nothing is eligible.  Iterates load-ordered so at most the
        chosen replica consumes a half-open trial slot."""
        with self._lock:
            order = sorted(
                (r for r in self._replicas
                 if r.alive and not r.draining
                 and r.index not in excluded),
                key=lambda r: (r.inflight, r.index))
        for r in order:
            if r.breaker.allow():
                return r
        return None

    def replicas(self):
        return list(self._replicas)

    def alive(self):
        """Liveness witness (the front door's /healthz reads it): at
        least one replica can serve."""
        return not self._closed and any(r.alive for r in self._replicas)

    def live_replicas(self):
        return [r.index for r in self._replicas if r.alive]

    def kill_replica(self, index):
        """Kill one replica (tests / chaos drills); the balancer
        converges to the survivors within one probe interval."""
        r = self._replica(index)
        if r is None:
            raise MXNetError("no replica with index %r" % (index,))
        was_alive = r.alive
        r.kill()
        if was_alive:
            self._note_death(r.index, "kill_replica")
            self._note_breaker(r)

    # -- elastic sizing ------------------------------------------------
    def add_replica(self):
        """Grow the set by one replica (the autoscaler's scale-up arm):
        take a registry from the warm spare pool if one is ready,
        otherwise build a fresh one from the constructor's factory —
        loading its OWN weight copy, outside the set lock — and join it
        to the rotation.  Returns the new replica's index (monotonic,
        never reused)."""
        if self._build is None:
            raise MXNetError(
                "this ReplicaSet was built from a fixed registry list; "
                "pass a callable build_registry to allow growth")
        with self._lock:
            if self._closed:
                raise ServeClosed("replica set is closed")
            index = self._next_index
            self._next_index += 1
            reg = self._spares.pop() if self._spares else None
        from_pool = reg is not None
        if reg is None:
            reg = self._build(index)
            if not isinstance(reg, ModelRegistry):
                raise MXNetError("replica %d: build_registry must yield "
                                 "a ModelRegistry, got %r" % (index, reg))
        r = self._new_replica(index, reg)
        with self._lock:
            closed = self._closed
            if not closed:
                self._replicas.append(r)
        if closed:
            # close() raced the build: never leak a running replica
            r.close(drain=False)
            raise ServeClosed("replica set is closed")
        self._note_breaker(r)
        _tracing.flight().record(
            "replica_added", "replica %d joined" % index, sid=index,
            from_pool=from_pool, live=self.live_replicas())
        return index

    def remove_replica(self, index=None, drain=True):
        """Shrink the set by one replica (the autoscaler's scale-down
        arm): take it out of rotation, then close it — draining its
        inflight requests by default, so scale-down under traffic loses
        nothing.  ``index=None`` removes the youngest live replica.
        The LAST replica is never removable.  Returns the removed
        index."""
        with self._lock:
            if len(self._replicas) <= 1:
                raise MXNetError(
                    "cannot remove the last replica of the set")
            if index is None:
                live = [r for r in self._replicas if r.alive]
                victim = max(live or self._replicas,
                             key=lambda r: r.index)
            else:
                victim = next((r for r in self._replicas
                               if r.index == index), None)
                if victim is None:
                    raise MXNetError("no replica with index %r"
                                     % (index,))
            # out of the list first: _pick stops routing to it before
            # the (possibly slow) drain below
            self._replicas.remove(victim)
            was_alive = victim.alive
        victim.close(drain=drain)
        # a cleanly drained registry goes back into the warm pool (a
        # KILLED replica's does not — its death is the point); the next
        # scale-up reuses the loaded weights and compiled programs
        with self._lock:
            if (was_alive and not self._closed
                    and len(self._spares) < self._spare_cap):
                self._spares.append(victim.registry)
        # retire the removed replica's gauges; its index is never
        # reused, so a stale series would claim a replica that cannot
        # come back
        _metrics.drop(dict(self._mlabels, replica=str(victim.index)))
        _tracing.flight().record(
            "replica_removed", "replica %d left" % victim.index,
            sid=victim.index, live=self.live_replicas())
        return victim.index

    def n_replicas(self):
        with self._lock:
            return len(self._replicas)

    def load_signals(self):
        """One sample of the sensor signals the autoscaler ticks on:
        replica counts, total balancer-tracked inflight, the aggregate
        inflight capacity (None when any engine is unbounded) and the
        cumulative shed count (set-level surfaced sheds plus every
        replica engine's admission sheds — the controller windows the
        deltas)."""
        with self._lock:
            live = [r for r in self._replicas
                    if r.alive and not r.draining]
            n_replicas = len(self._replicas)
            n_spares = len(self._spares)
            inflight = sum(r.inflight for r in live)
        caps = [r.engine._max_inflight for r in live]
        capacity = sum(caps) if caps and all(caps) else None
        shed = self._stats.as_dict().get("shed", 0)
        for r in live:
            shed += r.engine._stats.as_dict().get("shed", 0)
        return {"n_replicas": n_replicas, "n_live": len(live),
                "n_spares": n_spares, "inflight": inflight,
                "capacity": capacity, "shed_total": shed}

    # -- forward requests ----------------------------------------------
    def submit(self, model, timeout=None, priority=None, tenant=None,
               **inputs):
        """Balanced forward submit; returns a Future resolving to the
        output arrays.  ``timeout`` is the END-TO-END deadline: it
        propagates into each attempt's queue budget and bounds the
        whole retry chain.  ``priority`` / ``tenant`` ride through to
        the chosen replica's engine admission (tier preemption and
        per-tenant quotas — ``scheduler.ServingEngine.submit``)."""
        fut = Future()
        # trace context: captured here (an HTTP ingress trace, or a
        # fresh mint for bare in-process callers) and re-activated by
        # every placement attempt — retries on other replicas stay
        # spans of the SAME trace
        ctx = _tracing.current_context()
        owned = None
        if ctx is None:
            owned = _tracing.start_trace("serve.forward", model=model)
            ctx = (owned, owned.root_id)
        state = {
            "model": model, "inputs": inputs, "future": fut,
            "deadline": (time.monotonic() + timeout
                         if timeout is not None else None),
            "attempt": 0, "excluded": set(), "last_exc": None,
            "priority": priority, "tenant": tenant,
            "trace": ctx[0], "trace_parent": ctx[1],
        }
        if owned is not None:
            fut.add_done_callback(_tracing.finish_on_done(owned))
        self._stats.inc("submitted")
        self._dispatch(state)
        return fut

    def _dispatch(self, state):
        """One placement attempt: pick a replica, cross the faultinject
        seam, submit to its engine.  Retryable failures (replica died /
        engine closed / severed) reroute; ServeOverloaded excludes the
        replica and tries the next immediately; when nothing is left
        the request resolves with the structured last error.  Runs on
        the submitting thread or a retry timer thread — never on an
        engine thread."""
        with _tracing.activate(state["trace"], state["trace_parent"]):
            self._dispatch_traced(state)

    def _dispatch_traced(self, state):
        t0 = time.perf_counter_ns()
        while True:
            t_att = time.perf_counter_ns()
            if state["deadline"] is not None \
                    and time.monotonic() > state["deadline"]:
                self._resolve(state["future"], exc=ServeTimeout(
                    "request deadline expired during replica failover "
                    "(last error: %r)" % (state["last_exc"],)))
                return
            r = self._pick(state["excluded"])
            if r is None:
                self._resolve_no_replica(state)
                return
            try:
                faultinject.hook(SEAM, kind="forward", sid=r.index,
                                 model=state["model"])
                if not r.alive:
                    raise ReplicaDied("replica %d is dead" % r.index)
                remaining = None
                if state["deadline"] is not None:
                    remaining = max(0.0,
                                    state["deadline"] - time.monotonic())
                inner = r.engine.submit(state["model"], timeout=remaining,
                                        priority=state["priority"],
                                        tenant=state["tenant"],
                                        **state["inputs"])
            except ServeOverloaded as e:
                # this replica is at budget — others may have room.
                # The structured shed proves the engine is ALIVE, so
                # report success to the breaker (a consumed half-open
                # trial slot must be released or the replica wedges
                # out of rotation when the prober is disabled)
                r.breaker.record_success()
                state["excluded"].add(r.index)
                state["last_exc"] = e
                continue
            except (ReplicaDied, ServeClosed, OSError) as e:
                r.breaker.record_failure(e)
                self._note_breaker(r)
                state["excluded"].add(r.index)
                state["last_exc"] = e
                # the failed attempt leaves a span in the request's
                # trace (we are inside its activation): a retried
                # request's trace shows every placement it tried
                _profiler.record_phase("serve_retry", t_att)
                if not self._schedule_retry(state):
                    return
                continue
            except MXNetError as e:
                # validation/config errors are not retryable, and this
                # may run on a retry-timer thread — resolve, never
                # raise.  The replica answered: healthy for the breaker
                r.breaker.record_success()
                self._resolve(state["future"], exc=e)
                return
            with self._lock:
                r.inflight += 1
            self._stats.inc("dispatched")
            inner.add_done_callback(
                lambda f, s=state, rep=r: self._inner_done(s, rep, f))
            _profiler.record_phase("serve_dispatch", t0)
            return

    def _schedule_retry(self, state):
        """Count one failover attempt; False = budget exhausted and the
        request was resolved with its last error."""
        state["attempt"] += 1
        self._stats.inc("retries")
        if state["attempt"] > self._retries:
            self._resolve(state["future"], exc=state["last_exc"])
            return False
        return True

    def _resolve_no_replica(self, state):
        last = state["last_exc"]
        if isinstance(last, ServeOverloaded):
            self._stats.inc("shed")
        else:
            self._stats.inc("no_live")
        if isinstance(last, ServeOverloaded):
            exc = last  # every live replica is at its inflight budget
        else:
            exc = NoLiveReplicas(
                "no live replica can serve this request (last error: %r)"
                % (last,))
        self._resolve(state["future"], exc=exc)

    def _inner_done(self, state, r, inner):
        """Completion of one replica attempt (runs on the replica
        engine's completer thread — schedule, never sleep, here)."""
        with self._lock:
            r.inflight -= 1
        if inner.cancelled():
            state["future"].cancel()
            return
        exc = inner.exception()
        if exc is None:
            r.breaker.record_success()
            self._resolve(state["future"], result=inner.result())
            return
        if isinstance(exc, (ReplicaDied, ServeClosed, OSError)):
            # the replica accepted the request but could not serve it
            # (killed / closed under us): a forward is idempotent —
            # fail over to a survivor after backoff
            r.breaker.record_failure(exc)
            self._note_breaker(r)
            state["excluded"].add(r.index)
            state["last_exc"] = exc
            self._stats.inc("failovers")
            if not self._schedule_retry(state):
                return
            delay = backoff_delay(state["attempt"] - 1, self._backoff,
                                  self._backoff * 16.0)
            timer = threading.Timer(delay, self._dispatch, args=(state,))
            timer.daemon = True
            timer.start()
            return
        # non-retryable (ServeTimeout, validation errors): as-is
        self._resolve(state["future"], exc=exc)

    def _resolve(self, fut, result=None, exc=None):
        try:
            if exc is not None:
                fut.set_exception(exc)
            else:
                fut.set_result(result)
        except InvalidStateError:
            pass  # client cancel raced the resolution: the cancel wins

    # -- generation requests -------------------------------------------
    def submit_gen(self, model, tokens, **kwargs):
        """Balanced generation submit; returns a Future resolving to a
        GenerationResult.  Placement failures retry like forwards
        (nothing is lost before admission), but once a replica accepts
        the request there is NO transparent retry: if the replica dies,
        its KV cache — and the partially sampled stream — died with it,
        and the future fails fast with :class:`ReplicaDied` so the
        client owns the resubmit decision."""
        fut = Future()
        state = {"attempt": 0, "excluded": set(), "last_exc": None}
        self._stats.inc("gen_submitted")
        # same trace discipline as forwards: the whole placement loop —
        # and the engine submit inside it — runs under the request's
        # trace, so placement retries stay spans of ONE trace
        ctx = _tracing.current_context()
        owned = None
        if ctx is None:
            owned = _tracing.start_trace("serve.generate", model=model)
            ctx = (owned, owned.root_id)
        if owned is not None:
            fut.add_done_callback(_tracing.finish_on_done(owned))
        with _tracing.activate(ctx[0], ctx[1]):
            return self._submit_gen_traced(model, tokens, fut, state,
                                           **kwargs)

    def _submit_gen_traced(self, model, tokens, fut, state, **kwargs):
        t0 = time.perf_counter_ns()
        while True:
            r = self._pick(state["excluded"])
            if r is None:
                last = state["last_exc"]
                self._resolve(fut, exc=last if isinstance(
                    last, ServeOverloaded) else NoLiveReplicas(
                    "no live replica can serve this generation "
                    "(last error: %r)" % (last,)))
                return fut
            if r.gen_engine is None:
                raise MXNetError("this ReplicaSet was built without "
                                 "generation engines (gen=True)")
            try:
                faultinject.hook(SEAM, kind="gen", sid=r.index,
                                 model=model)
                if not r.alive:
                    raise ReplicaDied("replica %d is dead" % r.index)
                inner = r.gen_engine.submit(model, tokens, **kwargs)
            except ServeOverloaded as e:
                r.breaker.record_success()   # alive, just at budget
                state["excluded"].add(r.index)
                state["last_exc"] = e
                continue
            except (ReplicaDied, ServeClosed, OSError) as e:
                r.breaker.record_failure(e)
                self._note_breaker(r)
                state["excluded"].add(r.index)
                state["last_exc"] = e
                state["attempt"] += 1
                self._stats.inc("retries")
                if state["attempt"] > self._retries:
                    self._resolve(fut, exc=e)
                    return fut
                continue
            except MXNetError as e:
                r.breaker.record_success()   # the replica answered
                self._resolve(fut, exc=e)
                return fut
            with self._lock:
                r.inflight += 1
            self._stats.inc("dispatched")
            inner.add_done_callback(
                lambda f, rep=r: self._gen_done(fut, rep, f))
            _profiler.record_phase("serve_dispatch", t0)
            return fut

    def _gen_done(self, fut, r, inner):
        with self._lock:
            r.inflight -= 1
        if inner.cancelled():
            fut.cancel()
            return
        exc = inner.exception()
        if exc is None:
            r.breaker.record_success()
            self._resolve(fut, result=inner.result())
            return
        if isinstance(exc, (ServeClosed, OSError)) and not r.alive:
            r.breaker.record_failure(exc)
            self._note_breaker(r)
            self._stats.inc("gen_aborted")
            exc = ReplicaDied(
                "generation was lost with replica %d (its KV state "
                "died); resubmit to regenerate" % r.index)
        self._resolve(fut, exc=exc)

    # -- health probing ------------------------------------------------
    def _probe_loop(self):
        while not self._probe_stop.wait(self._probe_interval):
            self.probe_once()

    def probe_once(self):
        """One health sweep (the prober's body; tests call it directly
        for clock-free determinism).  A probe crosses the same
        ``serve.dispatch`` seam as requests — seeded fault schedules
        see ``kind='probe'`` events — and the engine's ``alive()``
        (dispatch loop running, accepting submits) is the liveness
        witness; failures open the breaker, successes close it."""
        with self._lock:
            replicas = list(self._replicas)
        for r in replicas:
            try:
                faultinject.hook(SEAM, kind="probe", sid=r.index)
                if not r.alive:
                    raise ReplicaDied("replica %d is dead" % r.index)
                if not r.engine.alive():
                    # the engine's dispatch loop is gone (crashed or
                    # closed under us) even though nobody called
                    # kill(): the probe must NOT re-close the breaker
                    # or the set would flap this replica back into
                    # rotation every interval
                    raise ReplicaDied(
                        "replica %d's engine dispatch loop has exited"
                        % r.index)
                r.breaker.record_success()
            except BaseException as e:  # noqa: BLE001 — health verdict
                r.breaker.record_failure(e)
                self._stats.inc("probe_failures")
            self._note_breaker(r)

    # -- management ----------------------------------------------------
    def swap_params(self, name, arg_params, aux_params=None, rate=None,
                    drain_timeout=None):
        """Zero-downtime ROLLING hot weight swap.

        One live replica at a time: take it out of rotation (only while
        the others can carry the traffic — a one-replica set swaps in
        place, the store swap is atomic per dispatch), wait up to
        ``drain_timeout`` seconds (``MXNET_SERVE_SWAP_DRAIN_S``) for its
        inflight requests to finish, swap its registry, re-probe it
        (the ``serve.dispatch`` seam with ``kind='swap_probe'`` plus an
        engine liveness check), restore it to rotation, then pause
        ``rate`` seconds (``MXNET_SERVE_SWAP_RATE``) before the next
        replica.  A failed re-probe ABORTS the roll: every
        already-swapped replica is rolled back to the exact weight set
        it served (``registry.restore_params``) and the abort raises —
        a bad weight push never takes out more than the replica it was
        probed on.

        Traffic during the roll sees only coherent weight sets — old or
        new, never a mix — and never fails for the roll's sake: the
        drained replica's share is carried by the rest of the rotation.
        Returns ``{replica_index: new_version}`` over the replicas that
        were live when the roll started (ones that die mid-roll are
        skipped); raises :class:`NoLiveReplicas` when there is nothing
        to swap."""
        if rate is None:
            rate = float(get_env("MXNET_SERVE_SWAP_RATE"))
        if drain_timeout is None:
            drain_timeout = float(get_env("MXNET_SERVE_SWAP_DRAIN_S"))
        with self._lock:
            targets = [r for r in self._replicas if r.alive]
        if not targets:
            raise NoLiveReplicas("no live replica to swap %r on" % name)
        fl = _tracing.flight()
        out = {}
        swapped = []   # (replica, pre-swap snapshot), for rollback
        for pos, r in enumerate(targets):
            if not r.alive:
                continue   # died mid-roll: the prober's problem, not ours
            with self._lock:
                # park only while another replica can serve: _pick
                # skips draining replicas, so parking the sole survivor
                # would fail traffic instead of protecting it
                r.draining = any(o.alive and not o.draining
                                 and o is not r for o in self._replicas)
            try:
                if r.draining:
                    deadline = time.monotonic() + max(0.0, drain_timeout)
                    while time.monotonic() < deadline:
                        with self._lock:
                            busy = r.inflight
                        if not busy:
                            break
                        time.sleep(0.001)
                snap = r.registry.param_snapshot(name)
                out[r.index] = r.registry.swap_params(name, arg_params,
                                                      aux_params)
                swapped.append((r, snap))
                self._reprobe(r)
            except BaseException as e:  # noqa: BLE001 — abort the roll
                with self._lock:
                    r.draining = False
                self._rollback_swap(name, swapped)
                fl.record("swap_aborted", "rolling swap of %r" % name,
                          sid=r.index, error=repr(e),
                          rolled_back=[x.index for x, _ in swapped])
                raise MXNetError(
                    "rolling swap of %r aborted at replica %d (%r); "
                    "every swapped replica was rolled back to the old "
                    "weights" % (name, r.index, e)) from e
            with self._lock:
                r.draining = False
            fl.record("swap_rolled", "replica %d -> v%s"
                      % (r.index, out[r.index]), sid=r.index)
            if rate > 0 and pos + 1 < len(targets):
                time.sleep(rate)
        if not out:
            raise NoLiveReplicas("no live replica to swap %r on" % name)
        # the warm pool must follow the roll: a spare joining the
        # rotation AFTER a successful swap would otherwise serve the
        # old weights.  Spares have nothing in flight, so this is a
        # plain publish (best-effort — a spare that cannot take the
        # weights is dropped from the pool rather than served stale).
        with self._lock:
            spares = list(self._spares)
        for sreg in spares:
            try:
                sreg.swap_params(name, arg_params, aux_params)
            except BaseException as e:  # noqa: BLE001
                with self._lock:
                    if sreg in self._spares:
                        self._spares.remove(sreg)
                fl.record("swap_spare_dropped",
                          "spare registry dropped on swap failure",
                          error=repr(e))
        return out

    def _reprobe(self, r):
        """Post-swap readiness gate: the swap seam event (seeded
        schedules fail it deterministically) plus the same liveness
        witness the prober uses."""
        faultinject.hook(SEAM, kind="swap_probe", sid=r.index)
        if not r.alive or not r.engine.alive():
            raise ReplicaDied("replica %d failed its post-swap re-probe"
                              % r.index)
        r.breaker.record_success()

    def _rollback_swap(self, name, swapped):
        """Abort path: republish each swapped replica's pre-swap
        snapshot, newest first.  Best-effort per replica — a replica
        that died after its swap has nothing to roll back."""
        for r, snap in reversed(swapped):
            if not r.alive:
                continue
            try:
                r.registry.restore_params(name, snap)
            except BaseException as e:  # noqa: BLE001 — keep rolling back
                _tracing.flight().record(
                    "swap_rollback_failed", "replica %d" % r.index,
                    sid=r.index, error=repr(e))

    def stats(self):
        out = self._stats.as_dict()
        with self._lock:
            replicas = list(self._replicas)
            inflight = {r.index: r.inflight for r in replicas}
        out["replicas"] = {
            r.index: {"alive": r.alive, "breaker": r.breaker.state,
                      "draining": r.draining,
                      "inflight": inflight[r.index],
                      "engine": r.engine.stats()}
            for r in replicas}
        out["live"] = self.live_replicas()
        return out

    def close(self, drain=True, timeout=60.0):
        """Stop the prober, close every replica (draining by default),
        release the die-handler seam.  Idempotent."""
        if self._closed:
            return
        self._closed = True
        self._probe_stop.set()
        if self._prober is not None:
            self._prober.join(timeout)
        # deregister only OUR handler: a newer ReplicaSet may have
        # installed its own, and clobbering it would send the next
        # scheduled die through os._exit (the whole-process kill the
        # handler exists to avoid)
        if faultinject.die_handler(SEAM) is self._injected_die:
            faultinject.register_die_handler(SEAM, None)
        with self._lock:
            replicas = list(self._replicas)
            self._spares = []   # registries only — nothing to join
        for r in replicas:
            r.close(drain=drain)
        # retire this set's labeled series (incl. per-replica gauges)
        _metrics.drop(self._mlabels)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

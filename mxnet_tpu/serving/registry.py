"""Multi-model tenancy: N models served from one process.

Each registered model gets its own :class:`~.program_store.ProgramStore`
(its own bucket programs, weights and compile-cache stats); the
continuous batcher (:class:`~.scheduler.ServingEngine`) schedules across
all of them, never mixing models in one batch.  Models can be added from
live arrays, a ``save_checkpoint`` prefix/epoch pair, or a
``deploy.to_serving`` artifact, and removed at runtime (in-flight
requests for a removed model fail cleanly at dispatch).

Serving weight dtype: ``compute_dtype='bfloat16'`` (or the
``MXNET_SERVE_DTYPE`` default) casts floating weights once at load —
half the resident memory per tenant, the PR-4 ``compute_dtype`` policy
applied to the serving plane — and ``compute_dtype='int8'`` quantizes
FC weights once at load into ``(codes, scales)`` program arguments
(~4x less resident memory, dequantized in-graph through the fused
dequant-matmul door).  Both apply to generative models too
(``add_generative_model``), which additionally take ``kv_dtype`` /
``sample`` (``MXNET_SERVE_KV_DTYPE`` / ``MXNET_SERVE_SAMPLE``) for the
decode plane's cache precision and sampling placement.
"""
from __future__ import annotations

from ..analysis.lockcheck import make_lock
from ..base import MXNetError, get_env
from .program_store import GenerativeProgramStore, ProgramStore

__all__ = ["ModelRegistry"]


class ModelRegistry:
    """name -> :class:`ProgramStore` with thread-safe add/remove.

    Generative (autoregressive) models register through
    :meth:`add_generative_model` into their own namespace of
    :class:`GenerativeProgramStore` — same name space (a name is either
    a forward model or a generative one, never both), separate
    accessor (:meth:`gen_store`), because the two are driven by
    different engines (:class:`~.scheduler.ServingEngine` vs
    :class:`~.decode_engine.GenerationEngine`)."""

    def __init__(self):
        self._stores = {}
        self._gen_stores = {}
        self._drafts = {}       # target name -> draft GenerativeProgramStore
        self._lock = make_lock("serving.registry")

    def add_model(self, name, symbol, arg_params, aux_params=None,
                  input_shapes=None, compute_dtype=None, buckets=None,
                  max_programs=None, input_dtypes=None, device=None,
                  warmup=True):
        """Register a model; compiles every bucket ahead of traffic
        unless ``warmup=False``.  Returns the model's ProgramStore."""
        if input_shapes is None:
            raise MXNetError("add_model needs input_shapes "
                             "(name -> (batch, ...) template)")
        if compute_dtype is None:
            compute_dtype = get_env("MXNET_SERVE_DTYPE") or None
        store = ProgramStore(symbol, arg_params, aux_params or {},
                             input_shapes, name=name,
                             compute_dtype=compute_dtype, buckets=buckets,
                             max_programs=max_programs,
                             input_dtypes=input_dtypes, device=device)
        with self._lock:
            if name in self._stores or name in self._gen_stores:
                raise MXNetError("model %r is already registered" % name)
            self._stores[name] = store
        if warmup:
            try:
                store.warmup()
            except BaseException:
                # a model whose programs don't compile must not stay
                # registered (serveable-but-broken, and blocking the
                # name for a corrected retry)
                with self._lock:
                    self._stores.pop(name, None)
                raise
        return store

    def load_checkpoint(self, name, prefix, epoch, input_shapes, **kwargs):
        """Register from a ``prefix-symbol.json`` + ``prefix-NNNN.params``
        pair (``model.save_checkpoint`` layout); params are loaded once
        and stay device-resident."""
        from ..model import load_checkpoint
        sym, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return self.add_model(name, sym, arg_params, aux_params,
                              input_shapes, **kwargs)

    def load_artifact(self, name, path, **overrides):
        """Register from a ``deploy.to_serving`` artifact (symbol-json +
        params + shape-bucket metadata in one zip); keyword overrides
        win over the artifact's recorded settings."""
        from ..deploy import read_serving_artifact
        sym, arg_params, aux_params, meta = read_serving_artifact(path)
        kwargs = {
            "input_shapes": {k: tuple(v)
                             for k, v in meta["input_shapes"].items()},
            "input_dtypes": meta.get("input_dtypes"),
            "buckets": meta.get("bucket_edges"),
            "compute_dtype": meta.get("compute_dtype"),
        }
        kwargs.update(overrides)
        return self.add_model(name, sym, arg_params, aux_params, **kwargs)

    def add_generative_model(self, name, params, spec, warmup=True,
                             warmup_kv_depth=None, **kwargs):
        """Register an autoregressive LM for the decode plane.

        ``params`` — the ``transformer_lm`` symbol graph's trained
        argument arrays (a ``save_checkpoint``'s arg_params works
        directly); ``spec`` — ``transformer_lm.lm_spec(...)``.  Keyword
        args (``batch_buckets``, ``prompt_buckets``, ``kv_block``,
        ``kv_max``, ``compute_dtype``, ``kv_dtype``, ``sample``,
        ``max_programs``, ``device``) pass through to
        :class:`GenerativeProgramStore`; like :meth:`add_model`, an
        unset ``compute_dtype`` falls back to the ``MXNET_SERVE_DTYPE``
        default.  Compiles + executes every prefill/decode bucket
        program ahead of traffic unless ``warmup=False``.  Returns the
        store."""
        if kwargs.get("compute_dtype") is None:
            kwargs["compute_dtype"] = get_env("MXNET_SERVE_DTYPE") or None
        store = GenerativeProgramStore(params, spec, name=name, **kwargs)
        with self._lock:
            if name in self._stores or name in self._gen_stores:
                raise MXNetError("model %r is already registered" % name)
            self._gen_stores[name] = store
        if warmup:
            try:
                store.warmup(kv_depth=warmup_kv_depth)
            except BaseException:
                with self._lock:
                    self._gen_stores.pop(name, None)
                raise
        return store

    def add_draft_model(self, target_name, params, spec, spec_k=None,
                        warmup=True, compute_dtype=None, device=None):
        """Attach a small DRAFT LM to generative model ``target_name``
        for speculative decoding (``MXNET_SERVE_SPEC``).

        The draft gets its own :class:`GenerativeProgramStore` with the
        target's pool geometry COPIED (``kv_block``, ``kv_max``,
        ``pool_blocks``, ``prefill_chunk``, batch buckets, ``kv_dtype``,
        paged + in-graph sampling) so the decode engine can drive both
        planes through the same block tables — the draft holds its own
        pool arrays but shares the target's block allocator.  Warms the
        speculative program kinds on BOTH sides (the draft's lq=1
        proposal + prefill-mirror chunks, the target's lq=spec_k+1
        verify), so attaching a draft never compiles inside a served
        request.  ``spec_k`` defaults to ``MXNET_SERVE_SPEC_K``.
        Returns the draft store."""
        target = self.gen_store(target_name)
        if not target.paged or target.sample_mode != "graph":
            raise MXNetError(
                "speculative decoding needs model %r on the paged "
                "plane with in-graph sampling (paged=True, "
                "sample='graph'); got paged=%s sample=%r"
                % (target_name, target.paged, target.sample_mode))
        if spec_k is None:
            spec_k = int(get_env("MXNET_SERVE_SPEC_K"))
        if spec_k < 1:
            raise MXNetError("spec_k must be >= 1, got %d" % spec_k)
        if compute_dtype is None:
            compute_dtype = get_env("MXNET_SERVE_DTYPE") or None
        draft = GenerativeProgramStore(
            params, spec, name="%s.draft" % target_name,
            batch_buckets=target._batch_edges,
            prompt_buckets=target._prompt_edges,
            kv_block=target.kv_block, kv_max=target.kv_max,
            compute_dtype=compute_dtype,
            kv_dtype=str(target.kv_dtype), sample="graph",
            paged=True, prefill_chunk=target.prefill_chunk,
            pool_blocks=target.pool_blocks, device=device)
        # the engine reads the attached window size off the draft —
        # the verify programs are warmed for exactly this lq
        draft.spec_k = spec_k
        with self._lock:
            if target_name in self._drafts:
                raise MXNetError("model %r already has a draft attached"
                                 % target_name)
            self._drafts[target_name] = draft
        if warmup:
            try:
                draft.warm_spec_programs(spec_k, draft=True)
                target.warm_spec_programs(spec_k)
            except BaseException:
                with self._lock:
                    self._drafts.pop(target_name, None)
                raise
        return draft

    def draft_store(self, name):
        """Generative model ``name``'s attached draft store, or None
        when no draft is registered (the engine's spec gate)."""
        with self._lock:
            return self._drafts.get(name)

    def load_generative_checkpoint(self, name, prefix, epoch, spec,
                                   **kwargs):
        """Register a generative model from a ``save_checkpoint``
        prefix/epoch pair (the symbol json is ignored — the decode
        graphs reuse the trained ARG arrays by name)."""
        from ..model import load_checkpoint
        _, arg_params, _ = load_checkpoint(prefix, epoch)
        return self.add_generative_model(name, arg_params, spec, **kwargs)

    def store(self, name):
        """The model's ProgramStore; raises MXNetError when unknown."""
        with self._lock:
            store = self._stores.get(name)
            known = sorted(self._stores) if store is None else None
        if store is None:
            raise MXNetError("unknown serving model %r (registered: %s)"
                             % (name, known))
        return store

    def gen_store(self, name):
        """The model's GenerativeProgramStore; raises when unknown."""
        with self._lock:
            store = self._gen_stores.get(name)
            known = sorted(self._gen_stores) if store is None else None
        if store is None:
            raise MXNetError(
                "unknown generative serving model %r (registered: %s)"
                % (name, known))
        return store

    def swap_params(self, name, arg_params, aux_params=None):
        """Hot weight swap under traffic: atomically republish model
        ``name``'s device-resident weight arguments (the programs take
        params as ARGUMENTS — no recompile).  Works for forward stores
        (``aux_params`` optionally refreshes auxiliary states) and
        generative stores (``aux_params`` must be None).  Every
        in-flight request executes against exactly one version — see
        the stores' ``swap_params`` docstrings; the new version shows
        up in ``stats()``.  Returns the new version number."""
        with self._lock:
            store = self._stores.get(name)
            gstore = self._gen_stores.get(name)
        if store is not None:
            return store.swap_params(arg_params, aux_params)
        if gstore is not None:
            if aux_params is not None:
                raise MXNetError("generative models have no auxiliary "
                                 "states to swap")
            return gstore.swap_params(arg_params)
        raise MXNetError("unknown serving model %r" % name)

    def param_snapshot(self, name):
        """Opaque handle to model ``name``'s live weight set (forward
        or generative store), for :meth:`restore_params` — captured by
        the replica set's rolling swap before each per-replica swap so
        a failed re-probe can roll back."""
        with self._lock:
            store = self._stores.get(name)
            gstore = self._gen_stores.get(name)
        if store is not None:
            return store.param_snapshot()
        if gstore is not None:
            return gstore.param_snapshot()
        raise MXNetError("unknown serving model %r" % name)

    def restore_params(self, name, snap):
        """Republish a :meth:`param_snapshot` (rolling-swap abort
        path).  Returns the new — still monotonic — version."""
        with self._lock:
            store = self._stores.get(name)
            gstore = self._gen_stores.get(name)
        if store is not None:
            return store.restore_params(snap)
        if gstore is not None:
            return gstore.restore_params(snap)
        raise MXNetError("unknown serving model %r" % name)

    def remove_model(self, name):
        with self._lock:
            self._drafts.pop(name, None)
            if self._stores.pop(name, None) is None and \
                    self._gen_stores.pop(name, None) is None:
                raise MXNetError("unknown serving model %r" % name)

    def models(self):
        with self._lock:
            return sorted(list(self._stores) + list(self._gen_stores))

    def stats(self):
        """Per-model program-store stats (compile cache, buckets)."""
        with self._lock:
            stores = dict(self._stores)
            stores.update(self._gen_stores)
        return {name: s.stats() for name, s in stores.items()}

    def __contains__(self, name):
        with self._lock:
            return name in self._stores or name in self._gen_stores

    def __len__(self):
        with self._lock:
            return len(self._stores) + len(self._gen_stores)

"""Read/transform images for neural nets: the pure-python image pipeline.

Reference: ``python/mxnet/image.py`` (559 LoC) — cv2-backed ``ImageIter``
with composable augmenter closures, plus the free-function crop/resize/
normalize zoo.  Host-side work stays on the host here too (augmentation is
branchy, per-sample, uint8 — wrong shape for the MXU); the TPU sees only
the final dense batch.  Backend is PIL+numpy (this image has no cv2);
arrays are HWC uint8/float32 numpy until ``postprocess_data`` transposes
to CHW.

Interp codes follow cv2 numbering like the reference (0=NEAREST, 1=LINEAR,
2=CUBIC ("AREA" in cv2 — mapped to PIL's closest), 3=LANCZOS).
"""
from __future__ import annotations

import logging
import os
import random

import numpy as np

from .base import MXNetError
from .io import io as _io_mod
from .io.image_util import _require_pil
from .io import recordio

__all__ = ["imdecode", "scale_down", "resize_short", "fixed_crop",
           "random_crop", "center_crop", "color_normalize",
           "random_size_crop", "ResizeAug", "RandomCropAug",
           "RandomSizedCropAug", "CenterCropAug", "RandomOrderAug",
           "ColorJitterAug", "LightingAug", "ColorNormalizeAug",
           "HorizontalFlipAug", "CastAug", "CreateAugmenter", "ImageIter",
           "rgb_to_hls", "hls_to_rgb", "hsl_jitter", "HLSJitterAug"]


def _pil_filter(interp):
    from PIL import Image
    return {0: Image.NEAREST, 1: Image.BILINEAR, 2: Image.BICUBIC,
            3: Image.LANCZOS, 4: Image.BOX}.get(int(interp), Image.BICUBIC)


def imdecode(buf, to_rgb=1, flag=1, **kwargs):
    """Decode an image byte buffer to an HWC numpy array (reference
    image.py:26 wraps cv2.imdecode)."""
    _require_pil()
    from PIL import Image
    import io as _bio
    img = Image.open(_bio.BytesIO(bytes(buf)))
    if flag == 0:
        img = img.convert("L")
        arr = np.asarray(img)[:, :, None]
    else:
        img = img.convert("RGB")
        arr = np.asarray(img)
        if not to_rgb:
            arr = arr[:, :, ::-1]  # cv2 default is BGR
    return arr


def scale_down(src_size, size):
    """Scale `size` down proportionally so it fits in `src_size`; a
    size that already fits is returned unchanged (role of reference
    image.py:62).

    The dimension that binds is set to the source bound EXACTLY (no
    float-ratio round-trip: int(truncation) of e.g. 343 * (49/343.)
    would undershoot to 48, or collapse a 1-pixel bound to 0)."""
    sw, sh = src_size
    w, h = size
    if w <= sw and h <= sh:
        return int(w), int(h)
    if w * sh >= h * sw:  # sw/w <= sh/h: width is the tighter bound
        return sw, int(h * sw / float(w))
    return int(w * sh / float(h)), sh


def _resize(src, w, h, interp=2):
    _require_pil()
    from PIL import Image
    dtype = src.dtype
    arr = np.asarray(src)
    if arr.ndim == 3 and arr.shape[2] == 1:
        arr = arr[:, :, 0]
    if np.issubdtype(dtype, np.floating):
        # float inputs (post-normalize pipelines) must not round-trip
        # through uint8 — resize each channel in PIL's 32-bit float mode
        chans = arr[..., None] if arr.ndim == 2 else arr
        out = np.stack([
            np.asarray(Image.fromarray(chans[:, :, c].astype(np.float32),
                                       mode="F")
                       .resize((int(w), int(h)), _pil_filter(interp)))
            for c in range(chans.shape[2])], axis=2)
        return out.astype(dtype)
    img = Image.fromarray(arr.astype(np.uint8))
    out = np.asarray(img.resize((int(w), int(h)), _pil_filter(interp)))
    if out.ndim == 2:
        out = out[:, :, None]
    return out.astype(dtype)


def resize_short(src, size, interp=2):
    """Resize the shorter edge to `size` (reference image.py:73)."""
    h, w = src.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return _resize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    """Crop [y0:y0+h, x0:x0+w], optionally resize to `size` (w, h)
    (reference image.py:83)."""
    out = src[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = _resize(out, size[0], size[1], interp)
    return out


def random_crop(src, size, interp=2):
    """Random crop of aspect-preserving `size`; returns (out, (x0, y0, w, h))
    (reference image.py:91)."""
    h, w = src.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = random.randint(0, w - new_w)
    y0 = random.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    """Center crop (reference image.py:103)."""
    h, w = src.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    """(src - mean) / std, HWC (reference image.py:115)."""
    src = src.astype(np.float32) - np.asarray(mean, dtype=np.float32)
    if std is not None:
        src = src / np.asarray(std, dtype=np.float32)
    return src


def random_size_crop(src, size, min_area, ratio, interp=2):
    """Random area+aspect crop, the Inception-style augmentation
    (reference image.py:123)."""
    h, w = src.shape[:2]
    area = w * h
    for _ in range(10):
        new_area = random.uniform(min_area, 1.0) * area
        new_ratio = random.uniform(*ratio)
        new_w = int(round(np.sqrt(new_area * new_ratio)))
        new_h = int(round(np.sqrt(new_area / new_ratio)))
        if random.random() < 0.5:
            new_w, new_h = new_h, new_w
        if new_w <= w and new_h <= h:
            x0 = random.randint(0, w - new_w)
            y0 = random.randint(0, h - new_h)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return random_crop(src, size, interp)


# ---------------------------------------------------------------------------
# Augmenter closures (each returns a list of outputs, reference style)
# ---------------------------------------------------------------------------
def ResizeAug(size, interp=2):
    """Make a resize-shorter-edge augmenter (reference image.py:147)."""
    def aug(src):
        return [resize_short(src, size, interp)]
    return aug


def RandomCropAug(size, interp=2):
    def aug(src):
        return [random_crop(src, size, interp)[0]]
    return aug


def RandomSizedCropAug(size, min_area, ratio, interp=2):
    def aug(src):
        return [random_size_crop(src, size, min_area, ratio, interp)[0]]
    return aug


def CenterCropAug(size, interp=2):
    def aug(src):
        return [center_crop(src, size, interp)[0]]
    return aug


def RandomOrderAug(ts):
    """Apply augmenters in random order (reference image.py:187)."""
    def aug(src):
        src = [src]
        ts_ = ts[:]
        random.shuffle(ts_)
        for t in ts_:
            src = [j for i in src for j in t(i)]
        return src
    return aug


def ColorJitterAug(brightness, contrast, saturation):
    """Random brightness/contrast/saturation jitter (reference
    image.py:201)."""
    ts = []
    coef = np.array([[[0.299, 0.587, 0.114]]], dtype=np.float32)
    if brightness > 0:
        def baug(src):
            alpha = np.float32(1.0 + random.uniform(-brightness, brightness))
            return [src * alpha]
        ts.append(baug)
    if contrast > 0:
        def caug(src):
            alpha = np.float32(1.0 + random.uniform(-contrast, contrast))
            gray = src * coef
            gray = np.float32((3.0 * (1.0 - alpha) / gray.size) * np.sum(gray))
            return [src * alpha + gray]
        ts.append(caug)
    if saturation > 0:
        def saug(src):
            alpha = np.float32(1.0 + random.uniform(-saturation, saturation))
            gray = np.sum(src * coef, axis=2, keepdims=True)
            return [src * alpha + gray * np.float32(1.0 - alpha)]
        ts.append(saug)
    return RandomOrderAug(ts)


def LightingAug(alphastd, eigval, eigvec):
    """PCA-noise lighting augmentation (reference image.py:241)."""
    def aug(src):
        alpha = np.random.normal(0, alphastd, size=(3,))
        rgb = np.dot(eigvec * alpha, eigval).astype(np.float32)
        return [src + rgb.reshape(1, 1, 3)]
    return aug


def ColorNormalizeAug(mean, std):
    def aug(src):
        return [color_normalize(src, mean, std)]
    return aug


def rgb_to_hls(arr):
    """Vectorized RGB->HLS on [0,1] float arrays (cv2 BGR2HLS analog;
    shared by the classification and detection HSL jitters)."""
    r, g, b = arr[..., 0], arr[..., 1], arr[..., 2]
    maxc = np.max(arr, axis=-1)
    minc = np.min(arr, axis=-1)
    l = (maxc + minc) / 2.0
    delta = maxc - minc
    s = np.where(delta == 0, 0.0,
                 np.where(l <= 0.5,
                          delta / np.maximum(maxc + minc, 1e-12),
                          delta / np.maximum(2.0 - maxc - minc, 1e-12)))
    dsafe = np.maximum(delta, 1e-12)
    rc = (maxc - r) / dsafe
    gc = (maxc - g) / dsafe
    bc = (maxc - b) / dsafe
    h = np.where(maxc == r, bc - gc,
                 np.where(maxc == g, 2.0 + rc - bc, 4.0 + gc - rc))
    h = np.where(delta == 0, 0.0, (h / 6.0) % 1.0)
    return h, l, s


def hls_to_rgb(h, l, s):
    """Inverse of :func:`rgb_to_hls`."""
    m2 = np.where(l <= 0.5, l * (1.0 + s), l + s - l * s)
    m1 = 2.0 * l - m2

    def channel(hue):
        hue = hue % 1.0
        return np.where(hue < 1 / 6, m1 + (m2 - m1) * hue * 6.0,
                        np.where(hue < 0.5, m2,
                                 np.where(hue < 2 / 3,
                                          m1 + (m2 - m1) *
                                          (2 / 3 - hue) * 6.0, m1)))
    return np.stack([channel(h + 1 / 3), channel(h),
                     channel(h - 1 / 3)], axis=-1)


def hsl_jitter(src, random_h=0, random_s=0, random_l=0, rng=None):
    """Random HSL shift on a 0..255 HWC float image (reference
    image_aug_default.cc random_h/random_s/random_l: additive uniform
    deltas on the cv2 HLS channels — H in degrees of the 0..180
    half-circle, S and L on the 0..255 scale).  ``rng`` (a
    ``np.random.Generator``) makes the draw deterministic; None keeps
    the legacy module-global ``np.random`` draw."""
    if not (random_h or random_s or random_l):
        return src
    uniform = np.random.uniform if rng is None else rng.uniform
    arr = np.clip(np.asarray(src, np.float32), 0, 255) / 255.0
    h, l, s = rgb_to_hls(arr)
    if random_h:
        h = h + uniform(-random_h, random_h) / 180.0
    if random_s:
        s = np.clip(s + uniform(-random_s, random_s) / 255.0,
                    0.0, 1.0)
    if random_l:
        l = np.clip(l + uniform(-random_l, random_l) / 255.0,
                    0.0, 1.0)
    out = hls_to_rgb(h, np.clip(l, 0, 1), np.clip(s, 0, 1))
    return np.clip(out * 255.0, 0, 255).astype(np.float32)


def HLSJitterAug(random_h, random_s, random_l):
    """Augmenter-list wrapper over :func:`hsl_jitter`."""
    def aug(src):
        return [hsl_jitter(src, random_h, random_s, random_l)]
    return aug


def HorizontalFlipAug(p):
    def aug(src):
        if random.random() < p:
            return [src[:, ::-1]]
        return [src]
    return aug


def CastAug():
    def aug(src):
        return [src.astype(np.float32)]
    return aug


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, pca_noise=0, random_h=0,
                    random_s=0, random_l=0, inter_method=2):
    """Build the standard augmenter pipeline (role of reference
    image.py:289): geometry first (resize, crop, flip), then cast, then
    photometric jitter, then normalization."""
    if rand_resize and not rand_crop:
        raise MXNetError("rand_resize requires rand_crop")
    out_wh = (data_shape[2], data_shape[1])
    cropper = (
        RandomSizedCropAug(out_wh, 0.3, (3.0 / 4.0, 4.0 / 3.0),
                           inter_method) if rand_resize
        else RandomCropAug(out_wh, inter_method) if rand_crop
        else CenterCropAug(out_wh, inter_method))

    # ImageNet defaults when the caller just says True
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None and std is None:
        raise MXNetError("mean normalization requires std")

    # ILSVRC RGB PCA basis (public AlexNet lighting-noise constants)
    pca_eigval = np.array([55.46, 4.794, 1.148])
    pca_eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])

    stages = [
        ResizeAug(resize, inter_method) if resize > 0 else None,
        cropper,
        HorizontalFlipAug(0.5) if rand_mirror else None,
        CastAug(),
        (ColorJitterAug(brightness, contrast, saturation)
         if brightness or contrast or saturation else None),
        # HLS-space jitter, the record-augmenter's random_h/s/l surface
        # (image_aug_default.cc) on the python ImageIter path
        (HLSJitterAug(random_h, random_s, random_l)
         if random_h or random_s or random_l else None),
        (LightingAug(pca_noise, pca_eigval, pca_eigvec)
         if pca_noise > 0 else None),
        ColorNormalizeAug(mean, std) if mean is not None else None,
    ]
    return [s for s in stages if s is not None]


class ImageIter(_io_mod.DataIter):
    """Image iterator with pipelined loading, partition support and
    python augmenters; reads .rec files or image lists
    (reference image.py:338)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root=None,
                 path_imgidx=None, shuffle=False, part_index=0, num_parts=1,
                 aug_list=None, imglist=None, data_name="data",
                 label_name="softmax_label", **kwargs):
        super().__init__(batch_size)
        if len(data_shape) != 3 or data_shape[0] != 3:
            raise MXNetError("data_shape must be (3, height, width)")
        self.data_shape = tuple(data_shape)
        self.batch_size = batch_size
        self.label_width = label_width
        self.imgrec = None
        self.imglist = None
        self.seq = None

        if path_imgrec:
            logging.info("ImageIter: loading recordio %s...", path_imgrec)
            if path_imgidx:
                self.imgrec = recordio.MXIndexedRecordIO(
                    path_imgidx, path_imgrec, "r")
                self.imgidx = list(self.imgrec.keys)
            else:
                self.imgrec = recordio.MXRecordIO(path_imgrec, "r")
                self.imgidx = None
        if path_imglist:
            logging.info("ImageIter: loading image list %s...", path_imglist)
            result = {}
            with open(path_imglist) as fin:
                for line in fin:
                    line = line.strip().split("\t")
                    label = np.array([float(i) for i in line[1:-1]],
                                     dtype=np.float32)
                    result[int(line[0])] = (label, line[-1])
            self.imglist = result
        elif isinstance(imglist, list):
            result = {}
            index = 1
            for img in imglist:
                key = str(index)
                index += 1
                if isinstance(img[0], (list, np.ndarray)):
                    label = np.array(img[0], dtype=np.float32)
                else:
                    label = np.array([img[0]], dtype=np.float32)
                result[key] = (label, img[1])
            self.imglist = result
        self.path_root = path_root

        if self.imglist is not None:
            self.seq = list(self.imglist.keys())
        elif self.imgrec is not None and self.imgidx is not None:
            self.seq = self.imgidx

        if (shuffle or num_parts > 1) and self.seq is None:
            raise MXNetError("shuffle/partitioning a .rec requires "
                             "path_imgidx (no random access without it)")
        if self.imgrec is not None and self.seq is not None and \
                self.imgidx is None:
            raise MXNetError("combining path_imgrec with an image list "
                             "requires path_imgidx (records are looked up "
                             "by list index)")
        if num_parts > 1:
            assert 0 <= part_index < num_parts
            N = len(self.seq)
            C = N // num_parts
            self.seq = self.seq[part_index * C:(part_index + 1) * C]

        self.shuffle = shuffle
        if aug_list is None:
            self.auglist = CreateAugmenter(data_shape, **kwargs)
        else:
            self.auglist = aug_list
        self.cur = 0
        self.provide_data = [_io_mod.DataDesc(data_name,
                                              (batch_size,) + self.data_shape)]
        if label_width > 1:
            self.provide_label = [_io_mod.DataDesc(
                label_name, (batch_size, label_width))]
        else:
            self.provide_label = [_io_mod.DataDesc(label_name, (batch_size,))]
        self.reset()

    def reset(self):
        if self.shuffle and self.seq is not None:
            random.shuffle(self.seq)
        if self.imgrec is not None:
            self.imgrec.reset()
        self.cur = 0

    def next_sample(self):
        """Return (label, decoded HWC image)."""
        if self.seq is None:
            # index-free mode: stream the record file in order
            packed = self.imgrec.read()
            if packed is None:
                raise StopIteration
            header, raw = recordio.unpack(packed)
            return header.label, imdecode(raw)
        if self.cur >= len(self.seq):
            raise StopIteration
        idx = self.seq[self.cur]
        self.cur += 1
        return self._sample_at(idx)

    def _sample_at(self, idx):
        """Random-access one sample by sequence index."""
        if self.imgrec is None:
            label, fname = self.imglist[idx]
            return label, self.read_image(fname)
        header, raw = recordio.unpack(self.imgrec.read_idx(idx))
        if self.imglist is not None:
            # combined mode: imglist relabels the rec contents
            return self.imglist[idx][0], imdecode(raw)
        return header.label, imdecode(raw)

    def next(self):
        batch_size = self.batch_size
        c, h, w = self.data_shape
        batch_data = np.zeros((batch_size, c, h, w), dtype=np.float32)
        if self.label_width > 1:
            batch_label = np.zeros((batch_size, self.label_width),
                                   dtype=np.float32)
        else:
            batch_label = np.zeros((batch_size,), dtype=np.float32)
        i = 0
        try:
            while i < batch_size:
                label, data = self.next_sample()
                data = self.augmentation_transform(data)
                for datum in data:
                    assert i < batch_size, \
                        "Batch size must be multiple of augmenter output"
                    batch_data[i] = self.postprocess_data(datum)
                    batch_label[i] = label
                    i += 1
        except StopIteration:
            if i == 0:
                raise
        from .ndarray import array
        pad = batch_size - i
        return _io_mod.DataBatch(data=[array(batch_data)],
                                 label=[array(batch_label)], pad=pad)

    def check_data_shape(self, data_shape):
        if not len(data_shape) == 3:
            raise ValueError("data_shape should have length 3, with "
                             "dimensions CxHxW")
        if not data_shape[0] == 3:
            raise ValueError("This iterator expects inputs to have 3 "
                             "channels.")

    def check_valid_image(self, data):
        if len(data[0].shape) == 0:
            raise RuntimeError("Data shape is wrong")

    def imdecode(self, s):
        return imdecode(s)

    def read_image(self, fname):
        path = os.path.join(self.path_root, fname) if self.path_root \
            else fname
        with open(path, "rb") as fin:
            return imdecode(fin.read())

    def augmentation_transform(self, data):
        data = [data]
        for aug in self.auglist:
            data = [ret for src in data for ret in aug(src)]
        return data

    def postprocess_data(self, datum):
        """HWC -> CHW float32."""
        return np.ascontiguousarray(
            np.asarray(datum, dtype=np.float32).transpose(2, 0, 1))

"""Optimizers.

Reference: ``python/mxnet/optimizer.py`` — registry, lr/wd multipliers
(``param_idx2name`` + ``__lr_mult__``/``__wd_mult__`` symbol attrs),
``clip_gradient``, ``rescale_grad``, per-index state, update counts;
SGD/NAG/Adam/AdaGrad/RMSProp/AdaDelta/Ftrl/SGLD/DCASGD/ccSGD/Test;
``get_updater`` closure (used locally and shipped to PS servers).

The hot updates call the fused registry ops (``sgd_update`` etc. —
reference ``src/operator/optimizer_op.cc``) so each update is one XLA
computation; python-math fallbacks cover the long tail.
"""
from __future__ import annotations

import math
import pickle

import numpy as np

from . import ndarray as nd
from .base import MXNetError
from .ndarray import NDArray

__all__ = ["Optimizer", "SGD", "NAG", "SGLD", "ccSGD", "DCASGD", "Adam",
           "AdaGrad", "RMSProp", "AdaDelta", "Ftrl", "Test", "create",
           "get_updater", "Updater", "register"]


class Optimizer:
    """Base class of the optimizer zoo (role of the reference's
    ``mxnet.optimizer.Optimizer``): per-parameter learning-rate /
    weight-decay multipliers, update counting, gradient rescale and
    clipping.  Subclasses define ``create_state`` + ``update``; under
    the fused Module path the same math runs in-graph on device
    (``parallel/ingraph_opt.py``)."""

    opt_registry = {}

    @staticmethod
    def register(klass):
        """Class decorator adding an Optimizer subclass to the
        by-name registry used by ``create``."""
        Optimizer.opt_registry[klass.__name__.lower()] = klass
        return klass

    @staticmethod
    def create_optimizer(name, **kwargs):
        """Instantiate a registered optimizer by (case-insensitive)
        name."""
        if name.lower() in Optimizer.opt_registry:
            return Optimizer.opt_registry[name.lower()](**kwargs)
        raise ValueError("Cannot find optimizer %s" % name)

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.clip_gradient = clip_gradient
        if param_idx2name is None:
            param_idx2name = {}
        self.idx2name = param_idx2name.copy()
        self.sym = sym
        self.set_lr_mult({})
        self.set_wd_mult({})

    def __getstate__(self):
        """Pickle support (dist kvstore ships the optimizer to the PS
        servers via command 0): drop the bound symbol — it exists only
        to harvest ``__lr_mult__``/``__wd_mult__`` attributes, which
        ``set_lr_mult``/``set_wd_mult`` already baked into plain dicts,
        and its compiled closures cannot pickle."""
        state = self.__dict__.copy()
        state["sym"] = None
        return state

    def create_state(self, index, weight):
        """Allocate the per-parameter optimizer state for ``weight``
        (None when the rule is stateless)."""
        return None

    def update(self, index, weight, grad, state):
        """Apply one update step to ``weight`` in place from ``grad``
        and this parameter's ``state``."""
        raise NotImplementedError()

    def set_lr_mult(self, args_lr_mult):
        """Per-parameter learning-rate multipliers (explicit dict wins
        over ``__lr_mult__`` symbol attributes)."""
        self.lr_mult = {}
        if self.sym is not None:
            attr = self.sym.attr_dict()
            for name in self.sym.list_arguments():
                if name in attr and "__lr_mult__" in attr[name]:
                    self.lr_mult[name] = float(attr[name]["__lr_mult__"])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        """Per-parameter weight-decay multipliers; biases/gammas
        default to 0 (no decay), ``__wd_mult__`` attributes and the
        explicit dict override."""
        self.wd_mult = {}
        for n in self.idx2name.values():
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        if self.sym is not None:
            attr = self.sym.attr_dict()
            for name in self.sym.list_arguments():
                if name in attr and "__wd_mult__" in attr[name]:
                    self.wd_mult[name] = float(attr[name]["__wd_mult__"])
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index],
                              self.num_update)

    def _get_lr(self, index):
        if self.lr_scheduler is not None:
            lr = self.lr_scheduler(self.num_update)
        else:
            lr = self.lr
        if index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd


register = Optimizer.register


@register
class SGD(Optimizer):
    """SGD with momentum; uses fused ``sgd_update``/``sgd_mom_update`` ops."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, weight.context,
                        dtype=str(weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        kwargs = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                      clip_gradient=(self.clip_gradient
                                     if self.clip_gradient else -1.0),
                      out=weight)
        if state is not None:
            nd.sgd_mom_update(weight, grad, state,
                              momentum=self.momentum, **kwargs)
        else:
            nd.sgd_update(weight, grad, **kwargs)


@register
class NAG(SGD):
    """Nesterov accelerated SGD (reference optimizer.py NAG)."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient,
                           a_max=self.clip_gradient)
        if state is not None:
            mom = state
            mom *= self.momentum
            grad += wd * weight
            mom += grad
            grad += self.momentum * mom
            weight -= lr * grad
        else:
            weight -= lr * (grad + wd * weight)


@register
class SGLD(Optimizer):
    """Stochastic Gradient Langevin Dynamics: SGD plus Gaussian noise
    scaled by the learning rate (Bayesian sampling)."""

    """Stochastic Gradient Langevin Dynamics."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient,
                           a_max=self.clip_gradient)
        noise = nd.array(np.random.normal(
            0, math.sqrt(lr), weight.shape), weight.context)
        weight -= lr / 2 * (grad + wd * weight)
        weight += noise


@register
class ccSGD(SGD):
    """Kept for API parity; same math as SGD (reference ccSGD was a C++
    fast-path of SGD)."""


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference optimizer.py DCASGD):
    corrects stale gradients with a curvature term."""

    """Delay-compensated async SGD (reference DCASGD)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (nd.zeros(weight.shape, weight.context), weight.copy())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient,
                           a_max=self.clip_gradient)
        mom, previous_weight = state
        if mom is not None:
            mom *= self.momentum
            mom += -lr * (grad + wd * weight + self.lamda *
                          grad * grad * (weight - previous_weight))
        else:
            mom = -lr * (grad + wd * weight + self.lamda *
                         grad * grad * (weight - previous_weight))
        previous_weight[:] = weight
        weight += mom


@register
class Adam(Optimizer):
    """Adam: bias-corrected first/second-moment adaptive steps; uses
    the fused ``adam_update`` op."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, weight.context,
                         dtype=str(weight.dtype)),
                nd.zeros(weight.shape, weight.context,
                         dtype=str(weight.dtype)))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        coef1 = 1. - self.beta1 ** t
        coef2 = 1. - self.beta2 ** t
        lr *= math.sqrt(coef2) / coef1
        mean, var = state
        nd.adam_update(weight, grad, mean, var, out=weight,
                       lr=lr, wd=wd, beta1=self.beta1, beta2=self.beta2,
                       epsilon=self.epsilon,
                       rescale_grad=self.rescale_grad,
                       clip_gradient=(self.clip_gradient
                                      if self.clip_gradient else -1.0))


@register
class AdaGrad(Optimizer):
    """AdaGrad: per-coordinate learning rates from accumulated squared
    gradients."""

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, weight.context)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient,
                           a_max=self.clip_gradient)
        history = state
        history += grad * grad
        weight -= lr * (grad / (history + self.float_stable_eps) ** 0.5 +
                        wd * weight)


@register
class RMSProp(Optimizer):
    """RMSProp (Tieleman/Hinton; centered Graves variant when
    ``centered=True``); uses the fused ``rmsprop_update`` /
    ``rmspropalex_update`` ops."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (nd.zeros(weight.shape, weight.context),
                    nd.zeros(weight.shape, weight.context),
                    nd.zeros(weight.shape, weight.context))
        return (nd.zeros(weight.shape, weight.context),)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        kwargs = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                      gamma1=self.gamma1, epsilon=self.epsilon,
                      clip_gradient=(self.clip_gradient
                                     if self.clip_gradient else -1.0),
                      clip_weights=(self.clip_weights
                                    if self.clip_weights else -1.0),
                      out=weight)
        if not self.centered:
            (n,) = state
            nd.rmsprop_update(weight, grad, n, **kwargs)
        else:
            n, g, delta = state
            nd.rmspropalex_update(weight, grad, n, g, delta,
                                  gamma2=self.gamma2, **kwargs)


@register
class AdaDelta(Optimizer):
    """AdaDelta: scale steps by the ratio of running RMS of updates to
    RMS of gradients (no explicit learning rate needed)."""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, weight.context),
                nd.zeros(weight.shape, weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient,
                           a_max=self.clip_gradient)
        acc_g, acc_delta = state
        acc_g[:] = self.rho * acc_g + (1. - self.rho) * grad * grad
        current_delta = ((acc_delta + self.epsilon) ** 0.5 /
                         (acc_g + self.epsilon) ** 0.5) * grad
        acc_delta[:] = self.rho * acc_delta + \
            (1. - self.rho) * current_delta * current_delta
        weight[:] = weight - current_delta - wd * weight


@register
class Ftrl(Optimizer):
    """FTRL-Proximal: L1/L2-regularized online learning (sparse
    models)."""

    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, weight.context),
                nd.zeros(weight.shape, weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient,
                           a_max=self.clip_gradient)
        z, n = state
        sigma = -(n ** 0.5)
        n += grad * grad
        sigma += n ** 0.5
        sigma /= lr
        z += grad - sigma * weight
        w_np = z.asnumpy()
        sign_z = np.sign(w_np)
        n_np = n.asnumpy()
        new_w = (sign_z * self.lamda1 - w_np) / \
            ((self.beta + n_np ** 0.5) / lr + wd)
        new_w *= (np.abs(w_np) > self.lamda1)
        weight[:] = nd.array(new_w, weight.context)._data


@register
class Test(Optimizer):
    """Trivial optimizer used by the reference test-suite: state is a
    weight-shaped buffer, update adds grad into it."""

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, weight.context)

    def update(self, index, weight, grad, state):
        weight += grad * self.rescale_grad
        state[:] = weight


def create(name, **kwargs):
    """Create a registered optimizer by name (``mx.optimizer.create("sgd", learning_rate=0.1)``)."""
    return Optimizer.create_optimizer(name, **kwargs)


class Updater:
    """Updater closure with per-index state (reference get_updater); picklable
    so dist kvstore can ship it to servers."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state(index, weight)
        self.optimizer.update(index, weight, grad, self.states[index])

    def set_states(self, states):
        states, counts, num_update = unpack_updater_states(states)
        if counts is not None:
            # v2 envelope: restore the optimizer's update counters too —
            # without them a resumed Adam restarts its bias-correction
            # schedule (t=0) and RMSProp-family warmups re-run
            self.optimizer._index_update_count = dict(counts)
            self.optimizer.num_update = num_update
        # numpy payloads from get_states come back as NDArrays so fused
        # update ops keep working after a checkpoint resume
        self.states = {k: _state_from_host(v) for k, v in states.items()}

    def get_states(self):
        return pack_updater_states({k: _state_to_host(v)
                                    for k, v in self.states.items()},
                                   self.optimizer)


def unpack_updater_states(obj):
    """Split an optimizer-states payload into ``(states, counts,
    num_update)``: accepts the bare ``{index: state}`` dict every
    pre-v2 checkpoint holds (counts come back None) or the v2 envelope
    ``Updater.get_states`` writes.  Shared by the host Updater and the
    fused trainer's checkpoint interop so both speak both formats."""
    if isinstance(obj, bytes):
        obj = pickle.loads(obj)
    if isinstance(obj, dict) and obj.get("__updater_format__") == 2:
        return obj["states"], obj["index_update_count"], obj["num_update"]
    return obj, None, None


def pack_updater_states(states, optimizer=None):
    """The v2 envelope for a host-layout ``{index: state}`` dict,
    carrying ``optimizer``'s update counters when given."""
    return pickle.dumps({
        "__updater_format__": 2,
        "states": states,
        "index_update_count":
            dict(optimizer._index_update_count) if optimizer else {},
        "num_update": optimizer.num_update if optimizer else 0,
    })


def _state_to_host(v):
    if isinstance(v, NDArray):
        return v.asnumpy()
    if isinstance(v, (tuple, list)):
        return tuple(_state_to_host(x) for x in v)
    return v


def _state_from_host(v):
    import numpy as _numpy
    if isinstance(v, _numpy.ndarray):
        return nd.array(v)
    if isinstance(v, (tuple, list)):
        return tuple(_state_from_host(x) for x in v)
    return v


def get_updater(optimizer):
    return Updater(optimizer)

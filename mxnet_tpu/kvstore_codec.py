"""Gradient-compression codec + fusion-bucket planner for the KVStore
data plane.

Reference: ``src/kvstore/gradient_compression.{h,cc}`` — MXNet's 2-bit
gradient compression quantizes each fp32 gradient element to one of
{-threshold, 0, +threshold} (2 bits each, 16 elements per emitted fp32
word there; 4 per byte here) and keeps the quantization error in a
per-worker *residual* that is added back into the next step's gradient
("error feedback"), so the compressed stream is unbiased over time and
SGD converges to the same loss as the fp32 stream.

Everything in this module is pure numpy and wire-format-only:

* :func:`quantize_codes` / :func:`pack_codes` / :func:`unpack_codes` /
  :func:`codes_to_float` — the stateless codec with exact size
  accounting (:func:`compressed_nbytes`);
* :class:`CompressedGrad` — one quantized gradient, sliceable into
  range-shard wire payloads without re-quantizing (quantization is
  elementwise, so a shard of the whole-array codes is identical to
  quantizing the shard);
* :class:`GradientCompression` — the per-worker stateful half:
  per-key negotiation (small / non-fp32 keys stay lossless) and the
  error-feedback residuals;
* :class:`BucketPlan` — deterministic greedy assignment of small keys
  to fixed-byte fusion buckets in init order, so one RPC can carry a
  whole bucket (``push_multi``/``pull_multi`` in kvstore_dist.py).

The wire payload for one compressed (range of a) gradient is the tuple
``("2bit", packed_bytes, n, threshold)`` — ``packed_bytes`` holds
``ceil(n/4)`` bytes, 4 codes per byte, code 1 = +threshold,
code 2 = -threshold, code 0 = zero.
"""
from __future__ import annotations

import zlib

import numpy as np

from .base import MXNetError, get_env

__all__ = ["quantize_codes", "pack_codes", "unpack_codes",
           "codes_to_float", "compressed_nbytes", "wire_nbytes",
           "is_compressed_payload", "payload_to_array", "payload_to_codes",
           "CompressedGrad", "GradientCompression", "BucketPlan"]

WIRE_TAG = "2bit"
# header bytes accounted per compressed payload beyond the packed codes
# (the (tag, n, threshold) fields of the wire tuple)
WIRE_HEADER_BYTES = 8


def quantize_codes(x, threshold):
    """Elementwise 2-bit quantization: int8 codes in {-1, 0, +1} for
    x >= t / |x| < t / x <= -t.  The represented value is
    ``codes * threshold``."""
    x = np.asarray(x, dtype=np.float32)
    return (np.where(x >= threshold, 1, 0)
            - np.where(x <= -threshold, 1, 0)).astype(np.int8)


def pack_codes(codes):
    """Pack int8 codes {-1,0,+1} 4-per-byte into ``bytes``
    (code +1 -> 0b01, -1 -> 0b10, 0 -> 0b00; element i sits at bit
    2*(i%4) of byte i//4)."""
    u = np.where(codes > 0, 1, np.where(codes < 0, 2, 0)).astype(np.uint8)
    pad = (-len(u)) % 4
    if pad:
        u = np.concatenate([u, np.zeros(pad, np.uint8)])
    u = u.reshape(-1, 4)
    packed = (u[:, 0] | (u[:, 1] << 2) | (u[:, 2] << 4)
              | (u[:, 3] << 6)).astype(np.uint8)
    return packed.tobytes()


def unpack_codes(packed, n):
    """Inverse of :func:`pack_codes`: first ``n`` int8 codes."""
    b = np.frombuffer(packed, dtype=np.uint8)
    two = np.stack([(b >> s) & 3 for s in (0, 2, 4, 6)], axis=1).reshape(-1)
    two = two[:n]
    return (np.where(two == 1, 1, 0)
            - np.where(two == 2, 1, 0)).astype(np.int8)


def codes_to_float(codes, threshold):
    return codes.astype(np.float32) * np.float32(threshold)


def compressed_nbytes(n):
    """Exact wire bytes for ``n`` compressed elements (packed codes +
    header); the fp32 equivalent is ``4 * n``."""
    return (n + 3) // 4 + WIRE_HEADER_BYTES


def wire_nbytes(payload):
    """Exact payload size (bytes-on-wire accounting) of one push/pull
    value: raw ndarrays count their buffer, compressed tuples count the
    packed codes + header."""
    if is_compressed_payload(payload):
        return len(payload[1]) + WIRE_HEADER_BYTES
    return np.asarray(payload).nbytes


def is_compressed_payload(payload):
    return (isinstance(payload, tuple) and len(payload) == 4
            and payload[0] == WIRE_TAG)


def payload_to_array(payload):
    """Decode one wire payload to a float32 array (lossless for raw
    payloads, dequantization for compressed ones)."""
    if is_compressed_payload(payload):
        _, packed, n, threshold = payload
        return codes_to_float(unpack_codes(packed, n), threshold)
    return np.asarray(payload, dtype=np.float32)


def payload_to_codes(payload):
    """Codes + threshold of a compressed payload (server-side exact
    merge accumulates int codes and multiplies by the threshold once)."""
    _, packed, n, threshold = payload
    return unpack_codes(packed, n), threshold


class CompressedGrad:
    """One quantized gradient, holding the full int8 code array so
    range shards can be cut without re-quantizing (elementwise codec:
    ``codes[lo:hi]`` equals quantizing ``x[lo:hi]``)."""

    __slots__ = ("codes", "threshold", "size")

    def __init__(self, codes, threshold):
        self.codes = codes
        self.threshold = float(threshold)
        self.size = codes.size

    def wire(self, lo=0, hi=None):
        hi = self.size if hi is None else hi
        return (WIRE_TAG, pack_codes(self.codes[lo:hi]), hi - lo,
                self.threshold)

    def dequantize(self, lo=0, hi=None):
        hi = self.size if hi is None else hi
        return codes_to_float(self.codes[lo:hi], self.threshold)


class GradientCompression:
    """Worker-side compression state: validated settings, per-key
    negotiation and error-feedback residuals.

    ``compress(key, flat)`` must be called in program order per key
    (the data-plane quantizes on the submitting thread, before the
    async pipeline reorders wire ops) so the residual stream — and
    therefore every pushed byte — is deterministic for a given call
    sequence."""

    def __init__(self, params):
        params = dict(params or {})
        ctype = params.pop("type", "none")
        if ctype not in ("none", "2bit"):
            raise MXNetError("unsupported gradient compression type %r "
                             "(supported: 'none', '2bit')" % (ctype,))
        self.type = ctype
        self.threshold = float(params.pop("threshold", 0.5))
        if params:
            raise MXNetError("unknown gradient compression parameters %r"
                             % sorted(params))
        if ctype != "none" and self.threshold <= 0:
            raise MXNetError("gradient compression threshold must be "
                             "positive, got %r" % self.threshold)
        self.lower_bound = int(get_env("MXNET_KVSTORE_COMPRESS_LOWER_BOUND"))
        self.residuals = {}

    @property
    def active(self):
        return self.type != "none"

    def negotiate(self, key, flat, orig_dtype=None):
        """Should pushes of this key be compressed?  Small keys and
        keys whose *source* array is not fp32 (indices, integer aux
        state — callers flatten to fp32 for the wire before asking, so
        they must pass the pre-cast dtype) stay lossless; ``init`` and
        ``pull`` payloads never come through here at all."""
        dtype = np.dtype(orig_dtype) if orig_dtype is not None \
            else flat.dtype
        return (self.active and flat.size >= self.lower_bound
                and dtype == np.float32)

    def compress(self, key, flat):
        """Quantize with error feedback; returns a CompressedGrad and
        updates this key's residual."""
        r = self.residuals.get(key)
        acc = flat + r if r is not None else flat.astype(np.float32, copy=True)
        codes = quantize_codes(acc, self.threshold)
        self.residuals[key] = acc - codes_to_float(codes, self.threshold)
        return CompressedGrad(codes, self.threshold)

    def get_residuals(self):
        """Residual state as plain numpy (checkpointable alongside
        optimizer state — error feedback is optimizer-adjacent state
        that must survive a restart for exact resume)."""
        return {k: v.copy() for k, v in self.residuals.items()}

    def set_residuals(self, residuals):
        self.residuals = {k: np.asarray(v, dtype=np.float32)
                          for k, v in (residuals or {}).items()}


class BucketPlan:
    """Deterministic fusion-bucket layout for small keys.

    Keys are assigned greedily in the order they are ``add``-ed (the
    kvstore init order, which Module fixes as parameter index order):
    a key whose payload would overflow the open bucket closes it and
    opens the next, keys at least as large as one bucket (or past the
    bigarray range-shard bound) stand alone.  The layout is a pure
    function of the (key, size) sequence, so every worker — and every
    restart of the same job — computes the same buckets, and server
    snapshots (which store per-key entries, never buckets) stay
    compatible across restarts by construction."""

    def __init__(self, bucket_bytes=None, bigarray_bound=None):
        self.bucket_bytes = int(get_env("MXNET_KVSTORE_BUCKET_BYTES")) \
            if bucket_bytes is None else int(bucket_bytes)
        self.bigarray_bound = int(get_env("MXNET_KVSTORE_BIGARRAY_BOUND")) \
            if bigarray_bound is None else int(bigarray_bound)
        self._assign = {}       # key -> bucket index (None = standalone)
        self._members = {}      # bucket index -> [key, ...]
        self._open = None       # (bucket index, used bytes)
        self._next = 0
        # versioned ownership deltas (live shard rebalancing): the
        # scheduler's plan version and its bucket->server overrides;
        # see docs/architecture/elastic_ps.md
        self.version = 0
        self._overrides = {}

    def add(self, key, size):
        """Assign ``key`` (``size`` fp32 elements); idempotent for a
        known key.  Returns the bucket index or None (standalone)."""
        if key in self._assign:
            return self._assign[key]
        nbytes = int(size) * 4
        if int(size) >= self.bigarray_bound or nbytes >= self.bucket_bytes:
            self._assign[key] = None
            return None
        if self._open is None or self._open[1] + nbytes > self.bucket_bytes:
            self._open = (self._next, 0)
            self._next += 1
        idx, used = self._open
        self._open = (idx, used + nbytes)
        self._assign[key] = idx
        self._members.setdefault(idx, []).append(key)
        return idx

    def bucket_of(self, key):
        """Bucket index of a known small key, else None (standalone /
        unknown keys keep the hashed or range-sharded path)."""
        return self._assign.get(key)

    def server_of(self, bucket, num_servers):
        """Deterministic BASE server owning a bucket (every member
        key's whole payload lives there, so one RPC covers the bucket).
        ``num_servers`` must be the INITIAL census — live rebalancing
        moves buckets exclusively through :meth:`apply_delta`
        overrides, never by reshuffling this hash."""
        return zlib.crc32(("bucket:%d" % bucket).encode()) % num_servers

    def apply_delta(self, version, overrides):
        """Adopt a newer versioned ownership delta from the scheduler
        (monotone: an older delta is ignored, so racing refreshes can
        arrive in any order)."""
        if version >= self.version:
            self.version = version
            self._overrides = dict(overrides)
        return self.version

    def owner_of(self, bucket, num_servers):
        """Current owner under the adopted deltas: the override when
        one exists, else the deterministic base assignment."""
        sid = self._overrides.get(bucket)
        return self.server_of(bucket, num_servers) if sid is None else sid

    def members(self, bucket):
        return list(self._members.get(bucket, ()))

    def layout(self):
        """Canonical (bucket, key) tuple — the determinism witness the
        restart-compatibility test compares across rebuilds."""
        return tuple((b, tuple(keys))
                     for b, keys in sorted(self._members.items())) + \
            tuple(("standalone", k) for k, b in self._assign.items()
                  if b is None)

/*
 * mxt_api.h — C training ABI for the mxnet_tpu framework.
 *
 * Role model: the training side of include/mxnet/c_api.h in the
 * reference (NDArray CRUD, MXImperativeInvoke, symbol compose,
 * MXExecutorBindEX + Forward/Backward, optimizer updates) — the surface
 * cpp-package/include/mxnet-cpp headers wrap to train models from C++.
 * The compute engine is XLA reached through JAX, so this library embeds
 * CPython running the mxnet_tpu package; all state lives behind opaque
 * int64 handles in a Python-side table (src/mxt_train_glue.py) and only
 * ints/flat float buffers cross this boundary.
 *
 * All functions return 0 on success, -1 on failure (MXTGetLastError for
 * the message, thread-local).  Handles are freed with MXTFree; freeing
 * is idempotent.  Calls are GIL-serialized internally — the ABI is
 * thread-safe but not parallel.
 */
#ifndef MXT_API_H_
#define MXT_API_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef int64_t MXTHandle;

const char *MXTGetLastError(void);

/* Initialize the embedded interpreter and import mxnet_tpu.
 * repo_root: directory containing the mxnet_tpu package (and
 * cpp-package/src for the glue).  Safe to call more than once. */
int MXTInit(const char *repo_root);

/* Free any handle kind (ndarray / symbol / executor / optimizer). */
int MXTFree(MXTHandle h);

/* Seed the framework RNG (mx.random.seed: jax keys + numpy, so weight
 * init through MXTNDArraySetUniform becomes deterministic). */
int MXTRandomSeed(int seed);

/* -- NDArray ------------------------------------------------------- */
int MXTNDArrayCreate(const int64_t *shape, int ndim, MXTHandle *out);
int MXTNDArrayFromData(const int64_t *shape, int ndim, const float *data,
                       MXTHandle *out);
/* Copy the array into out (size = element count, must match). */
int MXTNDArrayCopyTo(MXTHandle h, float *out, size_t size);
/* Write `size` float32 elements into the array (in place). */
int MXTNDArraySetData(MXTHandle h, const float *data, size_t size);
/* shape==NULL: only *ndim is written. */
int MXTNDArrayShape(MXTHandle h, int64_t *shape, int *ndim);
int MXTNDArraySetUniform(MXTHandle h, float lo, float hi);

/* Invoke a registered ndarray op: out = op(ins..., **{keys: vals}).
 * Attribute values are strings; the op registry's typed specs coerce
 * them (the reference C API has the same contract). */
int MXTImperativeInvoke(const char *op, const MXTHandle *ins, int nin,
                        const char **keys, const char **vals, int nkw,
                        MXTHandle *out);

/* -- Symbol -------------------------------------------------------- */
int MXTSymbolVariable(const char *name, MXTHandle *out);
int MXTSymbolCompose(const char *op, const char *name,
                     const MXTHandle *ins, int nin, const char **keys,
                     const char **vals, int nkw, MXTHandle *out);
/* JSON is copied into buf (cap bytes incl. NUL); *needed gets the full
 * length so callers can retry with a larger buffer. */
int MXTSymbolSaveJSON(MXTHandle h, char *buf, size_t cap, size_t *needed);
/* List arguments: call with names==NULL to get the count. Each name is
 * copied into the caller's buffers (name_cap bytes each). */
int MXTSymbolListArguments(MXTHandle h, char **names, int name_cap,
                           int *count);

/* -- Executor ------------------------------------------------------ */
/* grad_req: "write" | "null".  arg i has shapes[offsets[i]..+ndims[i]). */
int MXTExecutorSimpleBind(MXTHandle sym, const char *grad_req,
                          const char **arg_names, const int64_t *shapes,
                          const int *ndims, int n_args, MXTHandle *out);
int MXTExecutorForward(MXTHandle ex, int is_train);
int MXTExecutorBackward(MXTHandle ex);
int MXTExecutorNumOutputs(MXTHandle ex, int *out);
int MXTExecutorOutput(MXTHandle ex, int index, MXTHandle *out);
/* Bound argument / gradient arrays by name (live views: SetData on the
 * returned handle feeds the next Forward). */
int MXTExecutorArgArray(MXTHandle ex, const char *name, MXTHandle *out);
int MXTExecutorGradArray(MXTHandle ex, const char *name, MXTHandle *out);

/* -- Optimizer ----------------------------------------------------- */
int MXTOptimizerCreate(const char *name, const char **keys,
                       const char **vals, int nkw, MXTHandle *out);
/* In-place weight update; idx identifies the parameter (per-index
 * optimizer state, reference Optimizer semantics). */
int MXTOptimizerUpdate(MXTHandle opt, int idx, MXTHandle weight,
                       MXTHandle grad);

#ifdef __cplusplus
}
#endif

#endif  /* MXT_API_H_ */

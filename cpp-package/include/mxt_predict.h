/*
 * mxt_predict.h — minimal C prediction ABI for mxnet_tpu deploy
 * artifacts (.mxtpkg).
 *
 * Role model: include/mxnet/c_predict_api.h in the reference (MXPredCreate /
 * MXPredSetInput / MXPredForward / MXPredGetOutput / MXPredFree) — the
 * self-contained inference ABI that amalgamation and the non-Python
 * bindings consume.  Here the artifact already contains the compiled
 * StableHLO graph + weights; this ABI hosts a Python interpreter running
 * the single-file loader (amalgamation/mxnet_predict.py) behind plain C
 * functions, so any C/C++/FFI consumer can run inference without writing
 * a line of Python.
 *
 * All functions return 0 on success, -1 on failure (see
 * MXTPredGetLastError).  Not thread-safe across handles by design —
 * serialize calls per handle (the reference ABI has the same contract).
 */
#ifndef MXT_PREDICT_H_
#define MXT_PREDICT_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void *MXTPredHandle;

/* Last error message of the calling thread (static buffer). */
const char *MXTPredGetLastError(void);

/* Create a predictor from an .mxtpkg artifact on disk.
 * python_module_dir: directory holding mxnet_predict.py (the standalone
 * loader); pass NULL if it is already importable. */
int MXTPredCreate(const char *artifact_path, const char *python_module_dir,
                  MXTPredHandle *out);

/* Number of inputs / name of input i (borrowed pointer, valid until the
 * handle is freed). */
int MXTPredNumInputs(MXTPredHandle h, int *out);
int MXTPredGetInputName(MXTPredHandle h, int index, const char **out);

/* Set input `name` from a dense float32 buffer of `size` elements
 * (shape/dtype conversion happens inside; size must match the
 * artifact's declared input shape). */
int MXTPredSetInput(MXTPredHandle h, const char *name, const float *data,
                    size_t size);

/* Run the forward pass on the current inputs. */
int MXTPredForward(MXTPredHandle h);

/* Output arity / shape / data of output `index` after Forward.
 * MXTPredGetOutputShape: writes ndim to *ndim and up to *ndim dims into
 * shape (pass shape=NULL to query ndim only).
 * MXTPredGetOutput: copies `size` float32 elements into out. */
int MXTPredNumOutputs(MXTPredHandle h, int *out);
int MXTPredGetOutputShape(MXTPredHandle h, int index, int64_t *shape,
                          int *ndim);
int MXTPredGetOutput(MXTPredHandle h, int index, float *out, size_t size);

/* Release the predictor. */
int MXTPredFree(MXTPredHandle h);

#ifdef __cplusplus
}
#endif

#endif /* MXT_PREDICT_H_ */

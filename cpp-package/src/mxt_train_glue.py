"""Handle-table glue between the C training ABI and mxnet_tpu.

Role model: the reference's C API marshals every binding through
integer-safe handles (src/c_api/c_api.cc); here the handle table lives
on the Python side so the embedded-interpreter C layer
(cpp-package/src/mxt_api.cc) only ever passes ints and flat buffers —
no PyObject ownership crosses the boundary except transiently under the
GIL.

Every public function either returns a plain int/tuple/numpy array or
raises; the C layer converts exceptions into MXTGetLastError strings.
Attribute values arrive as strings and are coerced by the op registry's
typed AttrSpecs — exactly how the reference parses C-API kwargs into
dmlc parameter structs.
"""
from __future__ import annotations

import numpy as np

import mxnet_tpu as mx

_handles = {}
_next_handle = [1]


def _put(obj):
    h = _next_handle[0]
    _next_handle[0] += 1
    _handles[h] = obj
    return h


def _get(h):
    return _handles[h]


def free(h):
    _handles.pop(h, None)
    return 0


# -- ndarray ----------------------------------------------------------------
def nd_create(shape):
    return _put(mx.nd.zeros(tuple(int(d) for d in shape)))


def nd_from_numpy(arr):
    return _put(mx.nd.array(np.asarray(arr, dtype=np.float32)))


def nd_to_numpy(h):
    return np.ascontiguousarray(_get(h).asnumpy(), dtype=np.float32)


def nd_shape(h):
    return tuple(int(d) for d in _get(h).shape)


def nd_set_uniform(h, lo, hi):
    arr = _get(h)
    arr[:] = np.random.uniform(float(lo), float(hi), arr.shape) \
        .astype("float32")
    return 0


def nd_set_from_numpy(h, src):
    arr = _get(h)
    arr[:] = np.asarray(src, dtype=np.float32).reshape(arr.shape)
    return 0


def invoke(op, in_handles, keys, vals):
    fn = getattr(mx.nd, op, None)
    if fn is None:
        raise mx.base.MXNetError("unknown ndarray op %r" % op)
    out = fn(*[_get(h) for h in in_handles], **dict(zip(keys, vals)))
    return _put(out)


# -- symbol -----------------------------------------------------------------
def sym_variable(name):
    return _put(mx.sym.Variable(name))


def sym_compose(op, name, in_handles, keys, vals):
    fn = getattr(mx.sym, op, None)
    if fn is None:
        raise mx.base.MXNetError("unknown symbol op %r" % op)
    kwargs = dict(zip(keys, vals))
    if name:
        kwargs["name"] = name
    return _put(fn(*[_get(h) for h in in_handles], **kwargs))


def sym_to_json(h):
    return _get(h).tojson()


def sym_list_arguments(h):
    return list(_get(h).list_arguments())


def sym_list_outputs(h):
    return list(_get(h).list_outputs())


# -- executor ---------------------------------------------------------------
def simple_bind(sym_h, grad_req, names, shapes):
    kwargs = {n: tuple(int(d) for d in s) for n, s in zip(names, shapes)}
    ex = _get(sym_h).simple_bind(mx.current_context(), grad_req=grad_req,
                                 **kwargs)
    return _put(ex)


def executor_forward(h, is_train):
    _get(h).forward(is_train=bool(is_train))
    return 0


def executor_backward(h):
    _get(h).backward()
    return 0


def executor_num_outputs(h):
    return len(_get(h).outputs)


def executor_output(h, i):
    return _put(_get(h).outputs[int(i)])


def executor_arg(h, name):
    return _put(_get(h).arg_dict[name])


def executor_grad(h, name):
    grad = _get(h).grad_dict.get(name)
    if grad is None:
        raise mx.base.MXNetError("no gradient bound for %r" % name)
    return _put(grad)


# -- random -----------------------------------------------------------------
def seed(n):
    mx.random.seed(int(n))
    return 0


# -- optimizer --------------------------------------------------------------
def _coerce(v):
    if v in ("True", "true", "False", "false"):
        return v in ("True", "true")
    for cast in (int, float):
        try:
            return cast(v)
        except ValueError:
            pass
    return v


def optimizer_create(name, keys, vals):
    opt = mx.optimizer.create(name, **{
        k: _coerce(v) for k, v in zip(keys, vals)})
    # Updater owns the per-index lazy state exactly like the host
    # training path (and stays checkpoint-compatible via its
    # get_states/set_states)
    return _put(mx.optimizer.get_updater(opt))


def optimizer_update(opt_h, idx, weight_h, grad_h):
    _get(opt_h)(int(idx), _get(grad_h), _get(weight_h))
    return 0

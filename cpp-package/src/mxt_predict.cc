// mxt_predict.cc — C prediction ABI over .mxtpkg deploy artifacts.
//
// Reference role: src/c_api/c_predict_api.cc — the minimal, dependency-
// light inference ABI behind include/mxnet/c_predict_api.h.  The TPU
// stack's compute engine is XLA reached through JAX, so this library
// hosts an embedded CPython interpreter running the single-file loader
// (amalgamation/mxnet_predict.py, numpy+jax only) and marshals plain C
// buffers in and out.  No mxnet_tpu package is needed at runtime — only
// the artifact, python, numpy and jax.
//
// Build (see cpp-package/Makefile):
//   g++ -std=c++17 -O2 -fPIC -shared $(python3-config --includes) \
//       -o libmxt_predict.so src/mxt_predict.cc \
//       $(python3-config --ldflags --embed)

#include "../include/mxt_predict.h"

#include <Python.h>

#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "mxt_embed_common.h"

namespace {

using mxt_embed::Gil;
using mxt_embed::g_err;
using mxt_embed::set_err;
using mxt_embed::set_err_from_python;

struct Pred {
  PyObject *predictor = nullptr;           // mxnet_predict.Predictor
  std::vector<std::string> input_names;
  std::vector<PyObject *> outputs;         // numpy float32 C-contiguous
  std::vector<std::vector<int64_t>> out_shapes;

  ~Pred() {
    PyGILState_STATE gil = PyGILState_Ensure();
    for (PyObject *o : outputs) Py_XDECREF(o);
    Py_XDECREF(predictor);
    PyGILState_Release(gil);
  }
};

using mxt_embed::ensure_python;

PyObject *call_method(PyObject *obj, const char *name, PyObject *args) {
  PyObject *fn = PyObject_GetAttrString(obj, name);
  if (fn == nullptr) return nullptr;
  PyObject *r = PyObject_CallObject(fn, args);
  Py_DECREF(fn);
  return r;
}

}  // namespace

extern "C" {

const char *MXTPredGetLastError(void) { return g_err; }

int MXTPredCreate(const char *artifact_path, const char *python_module_dir,
                  MXTPredHandle *out) {
  if (artifact_path == nullptr || out == nullptr) {
    set_err("null argument");
    return -1;
  }
  if (!ensure_python()) {
    set_err("could not initialize python");
    return -1;
  }
  Gil gil;
  if (python_module_dir != nullptr) {
    PyObject *sys_path = PySys_GetObject("path");  // borrowed
    PyObject *dir = PyUnicode_FromString(python_module_dir);
    if (sys_path == nullptr || dir == nullptr ||
        PyList_Insert(sys_path, 0, dir) != 0) {
      Py_XDECREF(dir);
      set_err_from_python();
      return -1;
    }
    Py_DECREF(dir);
  }
  PyObject *mod = PyImport_ImportModule("mxnet_predict");
  if (mod == nullptr) {
    set_err_from_python();
    return -1;
  }
  PyObject *cls = PyObject_GetAttrString(mod, "Predictor");
  Py_DECREF(mod);
  if (cls == nullptr) {
    set_err_from_python();
    return -1;
  }
  PyObject *args = Py_BuildValue("(s)", artifact_path);
  PyObject *pred = PyObject_CallObject(cls, args);
  Py_DECREF(args);
  Py_DECREF(cls);
  if (pred == nullptr) {
    set_err_from_python();
    return -1;
  }
  Pred *p = new Pred;
  p->predictor = pred;
  PyObject *names = PyObject_GetAttrString(pred, "input_names");
  if (names == nullptr || !PyList_Check(names)) {
    Py_XDECREF(names);
    delete p;
    set_err("input_names not a list");
    return -1;
  }
  for (Py_ssize_t i = 0; i < PyList_Size(names); ++i) {
    p->input_names.emplace_back(PyUnicode_AsUTF8(PyList_GetItem(names, i)));
  }
  Py_DECREF(names);
  *out = p;
  return 0;
}

int MXTPredNumInputs(MXTPredHandle h, int *out) {
  if (h == nullptr || out == nullptr) {
    set_err("null argument");
    return -1;
  }
  *out = static_cast<int>(static_cast<Pred *>(h)->input_names.size());
  return 0;
}

int MXTPredGetInputName(MXTPredHandle h, int index, const char **out) {
  Pred *p = static_cast<Pred *>(h);
  if (p == nullptr || out == nullptr || index < 0 ||
      index >= static_cast<int>(p->input_names.size())) {
    set_err("bad input index");
    return -1;
  }
  *out = p->input_names[index].c_str();
  return 0;
}

int MXTPredSetInput(MXTPredHandle h, const char *name, const float *data,
                    size_t size) {
  Pred *p = static_cast<Pred *>(h);
  if (p == nullptr || name == nullptr || data == nullptr) {
    set_err("null argument");
    return -1;
  }
  Gil gil;
  // hand the buffer over as a list -> np.array inside set_input; shapes
  // are reshaped from the artifact's declared input shape
  PyObject *meta = PyObject_GetAttrString(p->predictor, "meta");
  if (meta == nullptr) {
    set_err_from_python();
    return -1;
  }
  PyObject *shapes = PyDict_GetItemString(meta, "input_shapes");  // borrowed
  PyObject *shape = shapes != nullptr
                        ? PyDict_GetItemString(shapes, name)  // borrowed
                        : nullptr;
  if (shape == nullptr) {
    Py_DECREF(meta);
    set_err("unknown input name");
    return -1;
  }
  int64_t want = 1;
  for (Py_ssize_t i = 0; i < PyList_Size(shape); ++i) {
    want *= PyLong_AsLongLong(PyList_GetItem(shape, i));
  }
  if (static_cast<int64_t>(size) != want) {
    Py_DECREF(meta);
    std::snprintf(g_err, sizeof(g_err),
                  "input %s: got %zu elements, artifact expects %lld", name,
                  size, static_cast<long long>(want));
    return -1;
  }
  // zero-copy wrap of the caller's buffer: memoryview -> np.frombuffer
  // -> reshape (set_input copies once into its own contiguous array, so
  // the view never outlives this call)
  PyObject *view = PyMemoryView_FromMemory(
      reinterpret_cast<char *>(const_cast<float *>(data)),
      static_cast<Py_ssize_t>(size * sizeof(float)), PyBUF_READ);
  PyObject *np = view != nullptr ? PyImport_ImportModule("numpy") : nullptr;
  PyObject *arr = nullptr;
  if (np != nullptr) {
    PyObject *frombuffer = PyObject_GetAttrString(np, "frombuffer");
    if (frombuffer != nullptr) {
      PyObject *a1 = PyObject_CallFunction(frombuffer, "Os", view,
                                           "float32");
      Py_DECREF(frombuffer);
      if (a1 != nullptr) {
        PyObject *reshape = PyObject_GetAttrString(a1, "reshape");
        if (reshape != nullptr) {
          arr = PyObject_CallFunctionObjArgs(reshape, shape, nullptr);
          Py_DECREF(reshape);
        }
        Py_DECREF(a1);
      }
    }
    Py_DECREF(np);
  }
  Py_XDECREF(view);
  Py_DECREF(meta);
  if (arr == nullptr) {
    set_err_from_python();
    return -1;
  }
  PyObject *args = Py_BuildValue("(sO)", name, arr);
  PyObject *r = call_method(p->predictor, "set_input", args);
  Py_DECREF(args);
  Py_DECREF(arr);
  if (r == nullptr) {
    set_err_from_python();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

int MXTPredForward(MXTPredHandle h) {
  Pred *p = static_cast<Pred *>(h);
  if (p == nullptr) {
    set_err("null handle");
    return -1;
  }
  Gil gil;
  PyObject *outs = call_method(p->predictor, "forward", nullptr);
  if (outs == nullptr) {
    set_err_from_python();
    return -1;
  }
  for (PyObject *o : p->outputs) Py_XDECREF(o);
  p->outputs.clear();
  p->out_shapes.clear();
  if (!PyList_Check(outs)) {
    Py_DECREF(outs);
    set_err("forward did not return a list");
    return -1;
  }
  for (Py_ssize_t i = 0; i < PyList_Size(outs); ++i) {
    PyObject *o = PyList_GetItem(outs, i);  // borrowed
    // normalize: float32 + C order + keep a flat memory view via tolist-
    // free path (astype returns a fresh contiguous array)
    PyObject *astype = PyObject_GetAttrString(o, "astype");
    if (astype == nullptr) {
      Py_DECREF(outs);
      set_err_from_python();
      return -1;
    }
    PyObject *of = PyObject_CallFunction(astype, "s", "float32");
    Py_DECREF(astype);
    if (of == nullptr) {
      Py_DECREF(outs);
      set_err_from_python();
      return -1;
    }
    PyObject *shape = PyObject_GetAttrString(of, "shape");
    std::vector<int64_t> dims;
    if (shape != nullptr && PyTuple_Check(shape)) {
      for (Py_ssize_t d = 0; d < PyTuple_Size(shape); ++d) {
        dims.push_back(PyLong_AsLongLong(PyTuple_GetItem(shape, d)));
      }
    }
    Py_XDECREF(shape);
    p->outputs.push_back(of);
    p->out_shapes.push_back(std::move(dims));
  }
  Py_DECREF(outs);
  return 0;
}

int MXTPredNumOutputs(MXTPredHandle h, int *out) {
  Pred *p = static_cast<Pred *>(h);
  if (p == nullptr || out == nullptr) {
    set_err("null argument");
    return -1;
  }
  *out = static_cast<int>(p->outputs.size());
  return 0;
}

int MXTPredGetOutputShape(MXTPredHandle h, int index, int64_t *shape,
                          int *ndim) {
  Pred *p = static_cast<Pred *>(h);
  if (p == nullptr || ndim == nullptr || index < 0 ||
      index >= static_cast<int>(p->outputs.size())) {
    set_err("bad output index (call Forward first)");
    return -1;
  }
  const std::vector<int64_t> &dims = p->out_shapes[index];
  // honor the caller's declared capacity in *ndim (header contract:
  // "up to *ndim dims"), then report the true rank
  int cap = *ndim;
  *ndim = static_cast<int>(dims.size());
  if (shape != nullptr) {
    int n = static_cast<int>(dims.size());
    if (cap > 0 && cap < n) n = cap;
    for (int i = 0; i < n; ++i) shape[i] = dims[i];
  }
  return 0;
}

int MXTPredGetOutput(MXTPredHandle h, int index, float *out, size_t size) {
  Pred *p = static_cast<Pred *>(h);
  if (p == nullptr || out == nullptr || index < 0 ||
      index >= static_cast<int>(p->outputs.size())) {
    set_err("bad output index (call Forward first)");
    return -1;
  }
  Gil gil;
  PyObject *o = p->outputs[index];
  Py_buffer view;
  if (PyObject_GetBuffer(o, &view, PyBUF_CONTIG_RO | PyBUF_FORMAT) != 0) {
    set_err_from_python();
    return -1;
  }
  size_t n = static_cast<size_t>(view.len) / sizeof(float);
  if (n != size) {
    PyBuffer_Release(&view);
    std::snprintf(g_err, sizeof(g_err),
                  "output %d has %zu elements, caller buffer %zu", index, n,
                  size);
    return -1;
  }
  std::memcpy(out, view.buf, view.len);
  PyBuffer_Release(&view);
  return 0;
}

int MXTPredFree(MXTPredHandle h) {
  delete static_cast<Pred *>(h);
  return 0;
}

}  // extern "C"

// mxt_embed_common.h — interpreter plumbing shared by the predict and
// training ABIs (each .so carries its own copy of the thread-local
// error buffer; the helpers must stay identical, which is why they
// live here and not pasted per file).
#ifndef MXT_EMBED_COMMON_H_
#define MXT_EMBED_COMMON_H_

#include <Python.h>

#include <cstdio>
#include <string>

namespace mxt_embed {

inline thread_local char g_err[2048];

inline void set_err(const char *what) {
  std::snprintf(g_err, sizeof(g_err), "%s", what);
}

// Capture the pending Python exception into g_err.
inline void set_err_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  std::string msg = "python error";
  if (value != nullptr) {
    PyObject *s = PyObject_Str(value);
    if (s != nullptr) {
      const char *c = PyUnicode_AsUTF8(s);
      if (c != nullptr) msg = c;
      Py_DECREF(s);
    }
  }
  set_err(msg.c_str());
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

// Initialize the interpreter; release the init-acquired GIL so
// PyGILState_Ensure nests correctly from any caller thread.
inline bool ensure_python() {
  if (Py_IsInitialized()) return true;
  Py_InitializeEx(0);
  PyEval_SaveThread();
  return Py_IsInitialized() != 0;
}

class Gil {
 public:
  Gil() : state_(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

}  // namespace mxt_embed

#endif  // MXT_EMBED_COMMON_H_

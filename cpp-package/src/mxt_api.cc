// mxt_api.cc — C training ABI over the embedded mxnet_tpu runtime.
//
// Reference role: the training slice of src/c_api/c_api.cc (NDArray
// CRUD, MXImperativeInvoke, symbol compose, executor bind/forward/
// backward, optimizer updates).  State lives in the Python-side handle
// table (src/mxt_train_glue.py); this file converts C buffers <-> numpy
// under the GIL and maps exceptions to MXTGetLastError.
//
// Build: see cpp-package/Makefile (libmxt.so target).

#include "../include/mxt_api.h"

#include <Python.h>

#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "mxt_embed_common.h"

namespace {

using mxt_embed::Gil;
using mxt_embed::g_err;
using mxt_embed::set_err;
using mxt_embed::set_err_from_python;

PyObject *g_glue = nullptr;  // mxt_train_glue module

// PyGILState_Ensure on an uninitialized interpreter is a fatal abort,
// so every entry point must bounce cleanly before touching the GIL.
#define MXT_REQUIRE_INIT()                          \
  do {                                              \
    if (!Py_IsInitialized() || g_glue == nullptr) { \
      set_err("MXTInit was not called");            \
      return -1;                                    \
    }                                               \
  } while (0)

// Call glue.<fn>(*args); returns new ref or nullptr (error already set).
PyObject *glue_call(const char *fn, PyObject *args) {
  if (g_glue == nullptr) {
    Py_XDECREF(args);
    set_err("MXTInit was not called");
    return nullptr;
  }
  PyObject *f = PyObject_GetAttrString(g_glue, fn);
  if (f == nullptr) {
    Py_XDECREF(args);
    set_err_from_python();
    return nullptr;
  }
  PyObject *r = PyObject_CallObject(f, args);
  Py_DECREF(f);
  Py_XDECREF(args);
  if (r == nullptr) set_err_from_python();
  return r;
}

// Call returning an int64 handle into *out.
int glue_call_handle(const char *fn, PyObject *args, MXTHandle *out) {
  PyObject *r = glue_call(fn, args);
  if (r == nullptr) return -1;
  *out = PyLong_AsLongLong(r);
  Py_DECREF(r);
  if (*out == -1 && PyErr_Occurred()) {
    set_err_from_python();
    return -1;
  }
  return 0;
}

// Call where the result is discarded (glue returns 0).
int glue_call_void(const char *fn, PyObject *args) {
  PyObject *r = glue_call(fn, args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

PyObject *shape_tuple(const int64_t *shape, int ndim) {
  PyObject *t = PyTuple_New(ndim);
  for (int i = 0; i < ndim; ++i)
    PyTuple_SET_ITEM(t, i, PyLong_FromLongLong(shape[i]));
  return t;
}

PyObject *str_list(const char **strs, int n) {
  PyObject *l = PyList_New(n);
  for (int i = 0; i < n; ++i) {
    // "replace" decoding: a non-UTF-8 C string must not plant a NULL
    // element (the glue would segfault iterating the list)
    PyObject *s = PyUnicode_DecodeUTF8(strs[i],
                                       static_cast<Py_ssize_t>(
                                           std::strlen(strs[i])),
                                       "replace");
    if (s == nullptr) {
      PyErr_Clear();
      s = PyUnicode_FromString("");
    }
    PyList_SET_ITEM(l, i, s);
  }
  return l;
}

PyObject *handle_list(const MXTHandle *hs, int n) {
  PyObject *l = PyList_New(n);
  for (int i = 0; i < n; ++i)
    PyList_SET_ITEM(l, i, PyLong_FromLongLong(hs[i]));
  return l;
}

// numpy float32 C-contiguous array object wrapping a COPY of data.
PyObject *numpy_from_buffer(const int64_t *shape, int ndim,
                            const float *data) {
  // build via python: np.frombuffer is zero-copy (unsafe); go through
  // bytes -> np.frombuffer(...).reshape(shape).copy() using the glue's
  // numpy to avoid linking numpy headers.
  size_t count = 1;
  for (int i = 0; i < ndim; ++i) count *= static_cast<size_t>(shape[i]);
  PyObject *np = PyImport_ImportModule("numpy");
  if (np == nullptr) return nullptr;
  PyObject *frombuffer = PyObject_GetAttrString(np, "frombuffer");
  Py_DECREF(np);
  if (frombuffer == nullptr) return nullptr;
  PyObject *bytes = PyBytes_FromStringAndSize(
      reinterpret_cast<const char *>(data),
      static_cast<Py_ssize_t>(count * sizeof(float)));
  PyObject *args = Py_BuildValue("(Os)", bytes, "float32");
  PyObject *flat = PyObject_CallObject(frombuffer, args);
  Py_DECREF(args);
  Py_DECREF(bytes);
  Py_DECREF(frombuffer);
  if (flat == nullptr) return nullptr;
  PyObject *shape_t = shape_tuple(shape, ndim);
  PyObject *reshaped = PyObject_CallMethod(flat, "reshape", "(O)", shape_t);
  Py_DECREF(shape_t);
  Py_DECREF(flat);
  if (reshaped == nullptr) return nullptr;
  PyObject *copy = PyObject_CallMethod(reshaped, "copy", nullptr);
  Py_DECREF(reshaped);
  return copy;
}

}  // namespace

extern "C" {

const char *MXTGetLastError(void) { return g_err; }

int MXTInit(const char *repo_root) {
  if (!mxt_embed::ensure_python()) {
    set_err("could not initialize python");
    return -1;
  }
  Gil gil;
  if (g_glue != nullptr) return 0;
  if (repo_root != nullptr) {
    PyObject *sys_path = PySys_GetObject("path");  // borrowed
    std::string root(repo_root);
    std::string glue_dir = root + "/cpp-package/src";
    for (const std::string &p : {root, glue_dir}) {
      PyObject *dir = PyUnicode_FromString(p.c_str());
      if (sys_path == nullptr || dir == nullptr ||
          PyList_Insert(sys_path, 0, dir) != 0) {
        Py_XDECREF(dir);
        set_err_from_python();
        return -1;
      }
      Py_DECREF(dir);
    }
  }
  g_glue = PyImport_ImportModule("mxt_train_glue");
  if (g_glue == nullptr) {
    set_err_from_python();
    return -1;
  }
  return 0;
}

int MXTFree(MXTHandle h) {
  MXT_REQUIRE_INIT();
  Gil gil;
  return glue_call_void("free", Py_BuildValue("(L)", h));
}

int MXTNDArrayCreate(const int64_t *shape, int ndim, MXTHandle *out) {
  MXT_REQUIRE_INIT();
  Gil gil;
  PyObject *args = Py_BuildValue("(N)", shape_tuple(shape, ndim));
  return glue_call_handle("nd_create", args, out);
}

int MXTNDArrayFromData(const int64_t *shape, int ndim, const float *data,
                       MXTHandle *out) {
  MXT_REQUIRE_INIT();
  Gil gil;
  PyObject *arr = numpy_from_buffer(shape, ndim, data);
  if (arr == nullptr) {
    set_err_from_python();
    return -1;
  }
  return glue_call_handle("nd_from_numpy", Py_BuildValue("(N)", arr), out);
}

int MXTNDArrayCopyTo(MXTHandle h, float *out, size_t size) {
  MXT_REQUIRE_INIT();
  Gil gil;
  PyObject *arr = glue_call("nd_to_numpy", Py_BuildValue("(L)", h));
  if (arr == nullptr) return -1;
  PyObject *bytes = PyObject_CallMethod(arr, "tobytes", nullptr);
  Py_DECREF(arr);
  if (bytes == nullptr) {
    set_err_from_python();
    return -1;
  }
  char *buf = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(bytes, &buf, &len) != 0 ||
      static_cast<size_t>(len) != size * sizeof(float)) {
    Py_DECREF(bytes);
    set_err("size mismatch in MXTNDArrayCopyTo");
    return -1;
  }
  std::memcpy(out, buf, len);
  Py_DECREF(bytes);
  return 0;
}

int MXTNDArraySetData(MXTHandle h, const float *data, size_t size) {
  MXT_REQUIRE_INIT();
  Gil gil;
  // flat 1-D buffer: the glue reshapes to the array's own shape and
  // raises on element-count mismatch, so no extra shape round-trip
  const int64_t flat = static_cast<int64_t>(size);
  PyObject *arr = numpy_from_buffer(&flat, 1, data);
  if (arr == nullptr) {
    set_err_from_python();
    return -1;
  }
  return glue_call_void("nd_set_from_numpy",
                        Py_BuildValue("(LN)", h, arr));
}

int MXTRandomSeed(int seed) {
  MXT_REQUIRE_INIT();
  Gil gil;
  return glue_call_void("seed", Py_BuildValue("(i)", seed));
}

int MXTNDArrayShape(MXTHandle h, int64_t *shape, int *ndim) {
  MXT_REQUIRE_INIT();
  Gil gil;
  PyObject *shp = glue_call("nd_shape", Py_BuildValue("(L)", h));
  if (shp == nullptr) return -1;
  int n = static_cast<int>(PyTuple_Size(shp));
  if (shape != nullptr)
    for (int i = 0; i < n && i < *ndim; ++i)
      shape[i] = PyLong_AsLongLong(PyTuple_GET_ITEM(shp, i));
  *ndim = n;
  Py_DECREF(shp);
  return 0;
}

int MXTNDArraySetUniform(MXTHandle h, float lo, float hi) {
  MXT_REQUIRE_INIT();
  Gil gil;
  return glue_call_void("nd_set_uniform",
                        Py_BuildValue("(Lff)", h, lo, hi));
}

int MXTImperativeInvoke(const char *op, const MXTHandle *ins, int nin,
                        const char **keys, const char **vals, int nkw,
                        MXTHandle *out) {
  MXT_REQUIRE_INIT();
  Gil gil;
  PyObject *args = Py_BuildValue("(sNNN)", op, handle_list(ins, nin),
                                 str_list(keys, nkw), str_list(vals, nkw));
  return glue_call_handle("invoke", args, out);
}

int MXTSymbolVariable(const char *name, MXTHandle *out) {
  MXT_REQUIRE_INIT();
  Gil gil;
  return glue_call_handle("sym_variable", Py_BuildValue("(s)", name), out);
}

int MXTSymbolCompose(const char *op, const char *name,
                     const MXTHandle *ins, int nin, const char **keys,
                     const char **vals, int nkw, MXTHandle *out) {
  MXT_REQUIRE_INIT();
  Gil gil;
  PyObject *args = Py_BuildValue(
      "(ssNNN)", op, name == nullptr ? "" : name, handle_list(ins, nin),
      str_list(keys, nkw), str_list(vals, nkw));
  return glue_call_handle("sym_compose", args, out);
}

int MXTSymbolSaveJSON(MXTHandle h, char *buf, size_t cap, size_t *needed) {
  MXT_REQUIRE_INIT();
  Gil gil;
  PyObject *s = glue_call("sym_to_json", Py_BuildValue("(L)", h));
  if (s == nullptr) return -1;
  Py_ssize_t len = 0;
  const char *c = PyUnicode_AsUTF8AndSize(s, &len);
  if (c == nullptr) {
    Py_DECREF(s);
    set_err_from_python();
    return -1;
  }
  if (needed != nullptr) *needed = static_cast<size_t>(len) + 1;
  if (buf != nullptr && cap > 0) {
    size_t n = static_cast<size_t>(len) < cap - 1
                   ? static_cast<size_t>(len) : cap - 1;
    std::memcpy(buf, c, n);
    buf[n] = '\0';
  }
  Py_DECREF(s);
  return 0;
}

int MXTSymbolListArguments(MXTHandle h, char **names, int name_cap,
                           int *count) {
  MXT_REQUIRE_INIT();
  Gil gil;
  PyObject *lst = glue_call("sym_list_arguments", Py_BuildValue("(L)", h));
  if (lst == nullptr) return -1;
  int n = static_cast<int>(PyList_Size(lst));
  if (names != nullptr) {
    for (int i = 0; i < n && i < *count; ++i) {
      const char *c = PyUnicode_AsUTF8(PyList_GET_ITEM(lst, i));
      std::snprintf(names[i], name_cap, "%s", c == nullptr ? "" : c);
    }
  }
  *count = n;
  Py_DECREF(lst);
  return 0;
}

int MXTExecutorSimpleBind(MXTHandle sym, const char *grad_req,
                          const char **arg_names, const int64_t *shapes,
                          const int *ndims, int n_args, MXTHandle *out) {
  MXT_REQUIRE_INIT();
  Gil gil;
  PyObject *names = str_list(arg_names, n_args);
  PyObject *shape_list = PyList_New(n_args);
  const int64_t *p = shapes;
  for (int i = 0; i < n_args; ++i) {
    PyList_SET_ITEM(shape_list, i, shape_tuple(p, ndims[i]));
    p += ndims[i];
  }
  PyObject *args = Py_BuildValue("(LsNN)", sym, grad_req, names,
                                 shape_list);
  return glue_call_handle("simple_bind", args, out);
}

int MXTExecutorForward(MXTHandle ex, int is_train) {
  MXT_REQUIRE_INIT();
  Gil gil;
  return glue_call_void("executor_forward",
                        Py_BuildValue("(Li)", ex, is_train));
}

int MXTExecutorBackward(MXTHandle ex) {
  MXT_REQUIRE_INIT();
  Gil gil;
  return glue_call_void("executor_backward", Py_BuildValue("(L)", ex));
}

int MXTExecutorNumOutputs(MXTHandle ex, int *out) {
  MXT_REQUIRE_INIT();
  Gil gil;
  PyObject *r = glue_call("executor_num_outputs", Py_BuildValue("(L)", ex));
  if (r == nullptr) return -1;
  *out = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXTExecutorOutput(MXTHandle ex, int index, MXTHandle *out) {
  MXT_REQUIRE_INIT();
  Gil gil;
  return glue_call_handle("executor_output",
                          Py_BuildValue("(Li)", ex, index), out);
}

int MXTExecutorArgArray(MXTHandle ex, const char *name, MXTHandle *out) {
  MXT_REQUIRE_INIT();
  Gil gil;
  return glue_call_handle("executor_arg",
                          Py_BuildValue("(Ls)", ex, name), out);
}

int MXTExecutorGradArray(MXTHandle ex, const char *name, MXTHandle *out) {
  MXT_REQUIRE_INIT();
  Gil gil;
  return glue_call_handle("executor_grad",
                          Py_BuildValue("(Ls)", ex, name), out);
}

int MXTOptimizerCreate(const char *name, const char **keys,
                       const char **vals, int nkw, MXTHandle *out) {
  MXT_REQUIRE_INIT();
  Gil gil;
  PyObject *args = Py_BuildValue("(sNN)", name, str_list(keys, nkw),
                                 str_list(vals, nkw));
  return glue_call_handle("optimizer_create", args, out);
}

int MXTOptimizerUpdate(MXTHandle opt, int idx, MXTHandle weight,
                       MXTHandle grad) {
  MXT_REQUIRE_INIT();
  Gil gil;
  return glue_call_void(
      "optimizer_update", Py_BuildValue("(LiLL)", opt, idx, weight, grad));
}

}  // extern "C"

// train_demo.cc — train an MLP classifier from C++ through the typed
// operator layer (mxt_op.h) over the mxt_api training ABI.
//
// Reference role: cpp-package/examples/mlp.cpp — the reference's C++
// package composes typed op calls from include/mxnet-cpp/op.h
// (OpWrapperGenerator output), simple_binds an Executor, and drives
// forward/backward/SGD from C++.  Same flow here over libmxt.so:
// synthetic blob-digit data (the same class-conditional gaussian bumps
// the python train_mnist example uses), 2-layer MLP composed as
// mxt::FullyConnected(...) / mxt::Activation(...) with compile-time
// checked attributes, softmax, SGD with momentum.  Exits 0 and prints
// "train accuracy" >0.9 when learning works end to end.
//
// Usage: ./train_demo <repo_root> [epochs]

#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <random>
#include <string>
#include <vector>

#include "../include/mxt_op.h"

namespace {

constexpr int kSide = 16;
constexpr int kFeat = kSide * kSide;
constexpr int kClasses = 10;
constexpr int kBatch = 64;

#define CHECK_OK(expr)                                            \
  do {                                                            \
    if ((expr) != 0) {                                            \
      std::fprintf(stderr, "FAILED %s: %s\n", #expr,              \
                   MXTGetLastError());                            \
      std::exit(1);                                               \
    }                                                             \
  } while (0)

// Class-conditional gaussian bumps (python examples' synthetic_digits).
void make_digits(std::mt19937 *rng, int n, std::vector<float> *xs,
                 std::vector<float> *ys) {
  std::uniform_real_distribution<float> noise(0.f, 0.15f);
  std::uniform_int_distribution<int> cls(0, kClasses - 1);
  xs->assign(static_cast<size_t>(n) * kFeat, 0.f);
  ys->assign(n, 0.f);
  for (int i = 0; i < n; ++i) {
    int y = cls(*rng);
    (*ys)[i] = static_cast<float>(y);
    float cx = 3.f + (y % 5) * 2.2f;
    float cy = 3.f + (y / 5) * 7.0f;
    for (int py = 0; py < kSide; ++py)
      for (int px = 0; px < kSide; ++px) {
        float d = ((px - cx) * (px - cx) + (py - cy) * (py - cy)) / 6.f;
        (*xs)[static_cast<size_t>(i) * kFeat + py * kSide + px] =
            std::exp(-d) + noise(*rng);
      }
  }
}

}  // namespace

int main(int argc, char **argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <repo_root> [epochs]\n", argv[0]);
    return 2;
  }
  int epochs = argc > 2 ? std::atoi(argv[2]) : 10;
  CHECK_OK(MXTInit(argv[1]));
  CHECK_OK(MXTRandomSeed(5));  // deterministic weight init

  // -- symbol: data -> fc(64) -> relu -> fc(10) -> softmax ----------
  // typed compose: attribute names/types are checked by the compiler
  // (mxt_op.h is generated from the op registry by tools/gen_cpp_ops.py)
  mxt::Symbol data = mxt::Symbol::Variable("data");
  mxt::Symbol fc1 = mxt::FullyConnected("fc1", data, /*num_hidden=*/64);
  mxt::Symbol act = mxt::Activation("relu1", fc1, /*act_type=*/"relu");
  mxt::Symbol fc2 = mxt::FullyConnected("fc2", act, /*num_hidden=*/10);
  mxt::Symbol net_s = mxt::SoftmaxOutput("softmax", fc2);
  MXTHandle net = net_s.handle();

  // -- bind ---------------------------------------------------------
  const char *bind_names[] = {"data", "softmax_label"};
  const int64_t bind_shapes[] = {kBatch, kFeat, kBatch};
  const int bind_ndims[] = {2, 1};
  MXTHandle ex = 0;
  CHECK_OK(MXTExecutorSimpleBind(net, "write", bind_names, bind_shapes,
                                 bind_ndims, 2, &ex));

  // -- parameters: list, init, collect grads ------------------------
  int n_args = 0;
  CHECK_OK(MXTSymbolListArguments(net, nullptr, 0, &n_args));
  std::vector<std::string> arg_names(n_args);
  {
    std::vector<char> store(static_cast<size_t>(n_args) * 64);
    std::vector<char *> ptrs(n_args);
    for (int i = 0; i < n_args; ++i) ptrs[i] = &store[i * 64];
    int cnt = n_args;
    CHECK_OK(MXTSymbolListArguments(net, ptrs.data(), 64, &cnt));
    for (int i = 0; i < n_args; ++i) arg_names[i] = ptrs[i];
  }
  std::vector<int> param_idx;
  std::vector<MXTHandle> weights, grads;
  for (int i = 0; i < n_args; ++i) {
    if (arg_names[i] == "data" || arg_names[i] == "softmax_label")
      continue;
    MXTHandle w = 0, g = 0;
    CHECK_OK(MXTExecutorArgArray(ex, arg_names[i].c_str(), &w));
    CHECK_OK(MXTExecutorGradArray(ex, arg_names[i].c_str(), &g));
    CHECK_OK(MXTNDArraySetUniform(w, -0.07f, 0.07f));
    param_idx.push_back(i);
    weights.push_back(w);
    grads.push_back(g);
  }

  MXTHandle data_arr = 0, label_arr = 0;
  CHECK_OK(MXTExecutorArgArray(ex, "data", &data_arr));
  CHECK_OK(MXTExecutorArgArray(ex, "softmax_label", &label_arr));

  // -- optimizer ----------------------------------------------------
  const char *okeys[] = {"learning_rate", "momentum", "rescale_grad"};
  char rescale[32];
  std::snprintf(rescale, sizeof(rescale), "%.8f", 1.0 / kBatch);
  const char *ovals[] = {"0.2", "0.9", rescale};
  MXTHandle opt = 0;
  CHECK_OK(MXTOptimizerCreate("sgd", okeys, ovals, 3, &opt));

  // -- data ---------------------------------------------------------
  std::mt19937 rng(7);
  const int n_train = 1024;
  std::vector<float> xs, ys;
  make_digits(&rng, n_train, &xs, &ys);

  // -- train --------------------------------------------------------
  const int batches = n_train / kBatch;
  for (int epoch = 0; epoch < epochs; ++epoch) {
    for (int b = 0; b < batches; ++b) {
      CHECK_OK(MXTNDArraySetData(
          data_arr, &xs[static_cast<size_t>(b) * kBatch * kFeat],
          static_cast<size_t>(kBatch) * kFeat));
      CHECK_OK(MXTNDArraySetData(label_arr, &ys[b * kBatch], kBatch));
      CHECK_OK(MXTExecutorForward(ex, 1));
      CHECK_OK(MXTExecutorBackward(ex));
      for (size_t p = 0; p < weights.size(); ++p)
        CHECK_OK(MXTOptimizerUpdate(opt, param_idx[p], weights[p],
                                    grads[p]));
    }
  }

  // -- evaluate -----------------------------------------------------
  int correct = 0, total = 0;
  std::vector<float> probs(static_cast<size_t>(kBatch) * kClasses);
  for (int b = 0; b < batches; ++b) {
    CHECK_OK(MXTNDArraySetData(
        data_arr, &xs[static_cast<size_t>(b) * kBatch * kFeat],
        static_cast<size_t>(kBatch) * kFeat));
    CHECK_OK(MXTExecutorForward(ex, 0));
    MXTHandle out = 0;
    CHECK_OK(MXTExecutorOutput(ex, 0, &out));
    CHECK_OK(MXTNDArrayCopyTo(out, probs.data(), probs.size()));
    CHECK_OK(MXTFree(out));
    for (int i = 0; i < kBatch; ++i) {
      int best = 0;
      for (int c = 1; c < kClasses; ++c)
        if (probs[i * kClasses + c] > probs[i * kClasses + best]) best = c;
      correct += best == static_cast<int>(ys[b * kBatch + i]);
      ++total;
    }
  }
  double acc = static_cast<double>(correct) / total;
  std::printf("train accuracy %.3f\n", acc);

  // symbol JSON round-trips through the ABI (checkpoint interop)
  size_t needed = 0;
  CHECK_OK(MXTSymbolSaveJSON(net, nullptr, 0, &needed));
  if (needed < 8) {
    std::fprintf(stderr, "suspicious symbol JSON size %zu\n", needed);
    return 1;
  }
  return acc > 0.9 ? 0 : 1;
}

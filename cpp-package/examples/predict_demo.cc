// predict_demo.cc — C++ consumer of the mxt_predict C ABI.
//
// Reference role: cpp-package/example + amalgamation's C predict demo —
// proves inference runs outside the Python package through plain C
// calls.  Usage:
//
//   ./predict_demo model.mxtpkg <loader_dir> <n_input_floats>
//
// Feeds ramp data into the first input, prints the first output's shape
// and leading values, exits 0 on success.

#include <cstdio>
#include <cstdlib>
#include <vector>

#include "../include/mxt_predict.h"

int main(int argc, char **argv) {
  if (argc != 4) {
    std::fprintf(stderr,
                 "usage: %s <model.mxtpkg> <loader_dir> <n_input_floats>\n",
                 argv[0]);
    return 2;
  }
  const char *artifact = argv[1];
  const char *loader_dir = argv[2];
  size_t n = static_cast<size_t>(std::atoll(argv[3]));

  MXTPredHandle h = nullptr;
  if (MXTPredCreate(artifact, loader_dir, &h) != 0) {
    std::fprintf(stderr, "create failed: %s\n", MXTPredGetLastError());
    return 1;
  }
  int n_in = 0;
  const char *in_name = nullptr;
  if (MXTPredNumInputs(h, &n_in) != 0 || n_in < 1 ||
      MXTPredGetInputName(h, 0, &in_name) != 0) {
    std::fprintf(stderr, "input query failed: %s\n", MXTPredGetLastError());
    return 1;
  }
  std::printf("inputs: %d, first: %s\n", n_in, in_name);

  std::vector<float> data(n);
  for (size_t i = 0; i < n; ++i) {
    data[i] = static_cast<float>(i % 17) / 17.0f - 0.5f;
  }
  if (MXTPredSetInput(h, in_name, data.data(), data.size()) != 0) {
    std::fprintf(stderr, "set_input failed: %s\n", MXTPredGetLastError());
    return 1;
  }
  if (MXTPredForward(h) != 0) {
    std::fprintf(stderr, "forward failed: %s\n", MXTPredGetLastError());
    return 1;
  }
  int n_out = 0, ndim = 0;
  if (MXTPredNumOutputs(h, &n_out) != 0 || n_out < 1 ||
      MXTPredGetOutputShape(h, 0, nullptr, &ndim) != 0) {
    std::fprintf(stderr, "output query failed: %s\n", MXTPredGetLastError());
    return 1;
  }
  std::vector<int64_t> shape(ndim);
  MXTPredGetOutputShape(h, 0, shape.data(), &ndim);
  size_t total = 1;
  std::printf("output 0 shape: [");
  for (int i = 0; i < ndim; ++i) {
    std::printf(i ? ", %lld" : "%lld", static_cast<long long>(shape[i]));
    total *= static_cast<size_t>(shape[i]);
  }
  std::printf("]\n");
  std::vector<float> out(total);
  if (MXTPredGetOutput(h, 0, out.data(), out.size()) != 0) {
    std::fprintf(stderr, "get_output failed: %s\n", MXTPredGetLastError());
    return 1;
  }
  std::printf("output 0 first values:");
  for (size_t i = 0; i < total && i < 4; ++i) std::printf(" %g", out[i]);
  std::printf("\nPREDICT_DEMO_OK\n");
  MXTPredFree(h);
  return 0;
}
